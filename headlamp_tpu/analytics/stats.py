"""Fleet stats — the serving-path entry to the XLA rollup.

One function, :func:`fleet_stats`, computes every dashboard aggregate
for a provider view. On hosts with jax, the TPU provider's stats come
from the fused XLA rollup (``fleet_jax.rollup_to_dict`` — one compiled
program per fleet-shape bucket, ADR-006); everywhere else — no jax, a
broken backend, or a provider whose device accessors the columnar
encoding doesn't carry (Intel) — the pure-Python fallback produces the
IDENTICAL key set, pinned together by the parity test at the 1024-node
fixture (``tests/test_analytics.py``).

Keys: capacity, allocatable, in_use, free, utilization_pct,
nodes_total, nodes_ready, phase_counts, generation_counts,
per_node_in_use, max_node_util_pct, hot_nodes.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

from ..domain import objects, tpu
from ..domain.accelerator import FleetView
from ..obs.metrics import registry as _metrics_registry
from ..obs.trace import annotate as _annotate
from ..obs.trace import span as _span

#: Node-utilization percentage at or above which a node counts as hot —
#: the UI kit's critical threshold (`NodesPage.tsx:38`).
HOT_NODE_PCT = 90.0


def _generation_counts(nodes: list[Any]) -> dict[str, int]:
    """Generation histogram preserving the ACTUAL inferred generation —
    a future 'tpu-v7x-slice' counts as 'v7x' and displays as 'TPU v7x'
    (format_generation's documented degradation), never as 'other'. The
    XLA rollup's histogram is vocabulary-bucketed (static shapes demand
    a fixed vocab), so :func:`fleet_stats` overrides its bucketed counts
    with this exact host-side pass — one O(nodes) loop against a fused
    program that already crossed the device boundary is noise, and it
    keeps the two backends byte-identical."""
    counts: dict[str, int] = {}
    for n in nodes:
        generation = tpu.get_node_generation(n)
        counts[generation] = counts.get(generation, 0) + 1
    return counts


def python_fleet_stats(view: FleetView) -> dict[str, Any]:
    """Pure-Python reference implementation: same aggregates, same key
    set, no jax. Also the numeric oracle the XLA rollup is tested
    against."""
    _annotate(backend="python")
    provider = view.provider
    summary = dict(
        objects.allocation_summary(
            view.nodes,
            view.pods,
            provider.node_device_capacity,
            provider.node_device_allocatable,
            provider.pod_device_request,
        )
    )

    nodes_ready = sum(1 for n in view.nodes if objects.is_node_ready(n))

    # Per-node in-use from Running pods, in view.nodes order.
    in_use_by_node: dict[str, int] = {}
    for pod in view.pods:
        if objects.pod_phase(pod) != "Running":
            continue
        node_name = objects.pod_node_name(pod)
        if node_name:
            in_use_by_node[node_name] = in_use_by_node.get(
                node_name, 0
            ) + provider.pod_device_request(pod)
    per_node_in_use = [in_use_by_node.get(objects.name(n), 0) for n in view.nodes]

    max_util = 0.0
    hot_nodes = 0
    for node, in_use in zip(view.nodes, per_node_in_use):
        allocatable = provider.node_device_allocatable(node)
        if allocatable <= 0:
            continue
        util = in_use / allocatable * 100.0
        max_util = max(max_util, util)
        if util >= HOT_NODE_PCT:
            hot_nodes += 1

    if provider.name == "tpu":
        generation_counts = _generation_counts(view.nodes)
    else:
        # Intel has no TPU generation vocabulary; its pages group by GPU
        # type separately.
        generation_counts = {}

    return {
        **summary,
        "nodes_total": len(view.nodes),
        "nodes_ready": nodes_ready,
        "phase_counts": objects.count_pod_phases(view.pods),
        "generation_counts": generation_counts,
        "per_node_in_use": per_node_in_use,
        "max_node_util_pct": float(max_util),
        "hot_nodes": hot_nodes,
    }


#: Fleet size below which the Python loops ALWAYS serve — no probe is
#: worth running there. Re-derived for the device-resident cache
#: (ADR-012): the old 512 floor was measured against the upload-
#: inclusive XLA path (encode + host→device transfer on every call);
#: with the fleet cached on device the rollup pays dispatch only, and
#: the measured cached-path crossover moves to ~64 nodes
#: (xla_cached 0.49 ms vs python 0.59 ms @ 62 nodes; 0.42 vs 0.31 @ 32
#: — r06 measurements on the CI host, recorded in OPERATIONS.md).
#: Below 64 nodes the Python pass is ≤ ~0.6 ms, which no probe can
#: repay. Above it the winner stays HOST-DEPENDENT — cached dispatch is
#: sub-ms on a local device but still one tunnel RTT (~89 ms) on a
#: tunneled one, while Python grows linearly (~0.01 ms/node) — so past
#: this floor the policy MEASURES both backends once per process and
#: picks the winner per request (ADR-006's "callers choose by scale",
#: upgraded to "chosen by measured per-host crossover"). The probe now
#: times the CACHED path when the view is versioned, i.e. exactly what
#: steady-state requests will serve.
XLA_ROLLUP_MIN_NODES = 64


#: Consecutive calibrate/XLA failures after which the process stops
#:  re-attempting device work (mirrors forecast.py's
#: `_record_pallas_broken` memoization — a persistently broken backend
#: must not re-pay a failed compile on every at-scale request).
CALIBRATE_BROKEN_AFTER = 3

#: Probe expiry. A single anomalous probe (tunnel blip, GC pause — the
#: median-of-3 narrows but cannot eliminate it) must not lock a
#: suboptimal backend for the process lifetime, and host conditions
#: drift. One re-probe per window is noise next to its ~600 ms worst
#: case. Deliberately NOT tied to /refresh: that is the routine header
#: link on every page, and per-click recalibration would re-pay the
#: probe constantly.
CALIBRATION_TTL_S = 15 * 60.0


class _Calibration:
    """Rollup timings, re-probed at most once per ``CALIBRATION_TTL_S``:
    one warm-up + timed XLA probe and a timed Python run at scale, then
    every later at-scale request inside the window picks the measured
    winner. Data fields are plain attribute writes (GIL-atomic); probe
    ENTRY is guarded by a non-blocking lock (``try_begin_probe``) so
    that under ThreadingHTTPServer only ONE request pays the ~600 ms+
    probe per window — every concurrent at-scale request that loses the
    race serves the stale measured winner (or, on a first-ever
    calibration with no measurement, the Python fallback) instead of
    stacking redundant probes. This matters at TTL expiry, where many
    in-flight requests can observe ``expired() == True`` in the same
    instant.

    Failure memoization: a host where jax imports but the backend is
    persistently broken would otherwise re-enter the probe (and re-pay
    the failed compile/dispatch) on EVERY at-scale request. After
    ``CALIBRATE_BROKEN_AFTER`` consecutive failures the last reason is
    pinned, ``chosen_backend`` answers "python" without touching the
    device, and /healthz surfaces the reason. The operator lever is
    ``reset()`` (wired to ``/refresh?recalibrate=1`` via the server's
    ``_force_recalibration``): it calls :meth:`clear_broken` to unpin
    the memoized failure AND drops the measured timings, forcing a
    fresh probe on the next at-scale request. A pinned broken state
    never expires by TTL (retrying a dead backend on a schedule is how
    the repeated-failure cost comes back)."""

    def __init__(self) -> None:
        # Created once per instance and deliberately NOT recreated by
        # reset(): a thread mid-probe must release the same lock it
        # acquired even if an operator resets underneath it.
        self._probe_lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        """Operator recovery lever (``/refresh?recalibrate=1``): drop
        measured timings and — via :meth:`clear_broken` — any pinned
        broken-backend state, so the next at-scale request re-probes."""
        #: (xla_ms, python_ms_per_node, calibrated_at) — ONE reference,
        #: swapped atomically by :meth:`publish`, so no concurrent
        #: reader can ever observe a mixed old/new calibration (e.g. a
        #: re-probe's fresh python timing against the previous window's
        #: xla timing). The three public names are properties over it.
        self._measured: tuple[float | None, float | None, float | None] | None = None
        self.clear_broken()

    def publish(
        self,
        *,
        xla_ms: float,
        python_ms_per_node: float,
        calibrated_at: float,
    ) -> None:
        """Publish a complete measurement in one atomic swap."""
        self._measured = (xla_ms, python_ms_per_node, calibrated_at)

    @property
    def xla_ms(self) -> float | None:
        m = self._measured
        return m[0] if m else None

    @xla_ms.setter
    def xla_ms(self, v: float | None) -> None:
        # Tests/benches pin fields one at a time; each write rebuilds
        # the tuple so concurrent readers still see one reference.
        m = self._measured or (None, None, None)
        self._measured = (v, m[1], m[2])

    @property
    def python_ms_per_node(self) -> float | None:
        m = self._measured
        return m[1] if m else None

    @python_ms_per_node.setter
    def python_ms_per_node(self, v: float | None) -> None:
        m = self._measured or (None, None, None)
        self._measured = (m[0], v, m[2])

    @property
    def calibrated_at(self) -> float | None:
        m = self._measured
        return m[2] if m else None

    @calibrated_at.setter
    def calibrated_at(self, v: float | None) -> None:
        m = self._measured or (None, None, None)
        self._measured = (m[0], m[1], v)

    def measured_winner(self, n_nodes: int) -> str | None:
        """The backend the last PUBLISHED measurement picks for an
        ``n_nodes`` fleet — "xla" or "python" — or ``None`` when no
        measurement exists. Reads the tuple once, so the comparison is
        always against one coherent calibration. Deliberately ignores
        the TTL: callers decide whether staleness matters (a mid-probe
        loser serves the stale winner; :func:`chosen_backend` re-probes
        instead)."""
        m = self._measured
        if m is None or m[0] is None:
            return None
        xla_ms, per_node, _ = m
        predicted = per_node * n_nodes if per_node is not None else None
        if predicted is not None and predicted < xla_ms:
            return "python"
        return "xla"

    def clear_broken(self) -> None:
        """Unpin a memoized broken backend (and its failure streak) so
        the next at-scale request re-probes. Measured timings survive —
        clearing them belongs to the TTL (or the full :meth:`reset`),
        not to this narrower unpin."""
        self.consecutive_failures = 0
        self.broken_reason = None

    def try_begin_probe(self) -> bool:
        """Claim the single probe slot (non-blocking). The winner must
        call :meth:`end_probe` when done; losers serve the stale
        measured winner (TTL re-probe) or the Python fallback (first
        calibration) for this request and re-check on their next."""
        return self._probe_lock.acquire(blocking=False)

    def end_probe(self) -> None:
        self._probe_lock.release()

    def expired(self, now: float) -> bool:
        return (
            self.calibrated_at is not None
            and now - self.calibrated_at > CALIBRATION_TTL_S
        )

    def record_failure(self, reason: str) -> None:
        self.consecutive_failures += 1
        if self.consecutive_failures >= CALIBRATE_BROKEN_AFTER and self.broken_reason is None:
            self.broken_reason = reason

    def record_success(self) -> None:
        self.consecutive_failures = 0


calibration = _Calibration()

# Calibration state as scrapeable gauges (ADR-013): callback views over
# the singleton above — /healthz's analytics block and /metricsz read
# the SAME _measured tuple, so they cannot drift. None (uncalibrated)
# omits the sample rather than fabricating a zero timing.
_metrics_registry.gauge_fn(
    "headlamp_tpu_calibration_xla_seconds",
    "Measured XLA rollup latency from the last calibration probe",
    lambda: calibration.xla_ms / 1000.0 if calibration.xla_ms is not None else None,
)
_metrics_registry.gauge_fn(
    "headlamp_tpu_calibration_python_per_node_seconds",
    "Measured Python rollup latency per node from the last calibration probe",
    lambda: (
        calibration.python_ms_per_node / 1000.0
        if calibration.python_ms_per_node is not None
        else None
    ),
)
_metrics_registry.gauge_fn(
    "headlamp_tpu_calibration_broken_info",
    "1 when the device backend is pinned broken (requests serve Python)",
    lambda: 1.0 if calibration.broken_reason is not None else 0.0,
)


def chosen_backend(n_nodes: int) -> str:
    """Which backend the default policy would serve an ``n_nodes`` fleet
    right now — "python", "xla", or "calibrating" (probe not yet run).
    Observability for benches/operators: the measured-winner policy must
    never leave callers guessing which path their numbers exercised."""
    if n_nodes < XLA_ROLLUP_MIN_NODES:
        return "python"
    if calibration.broken_reason is not None:
        return "python"
    winner = calibration.measured_winner(n_nodes)
    if winner is None or calibration.expired(time.monotonic()):
        return "calibrating"
    return winner


def fleet_stats(view: FleetView, *, backend: str | None = None) -> dict[str, Any]:
    """Serving-path aggregates for one provider view.

    Dispatch policy (TPU provider, jax-capable hosts): pure Python below
    ``XLA_ROLLUP_MIN_NODES`` (measured unbeatable there); at scale, the
    first request runs BOTH backends — an XLA warm-up (compile) plus a
    timed steady-state dispatch, and a timed Python pass — records both
    in :data:`calibration`, and serves the XLA result (the parity suite
    pins them equal); every later request picks whichever measured
    faster for its fleet size. ``backend`` ("xla"/"python") pins a path
    for tests and benches; an explicit "xla" pin propagates every
    failure — missing jax, broken rollup, non-TPU provider — instead of
    silently degrading, so a parity test on a jax-less host must skip,
    not vacuously compare Python to itself. On the default path any
    jax-side failure falls back: analytics acceleration must never cost
    a page.

    Traced as ``analytics.rollup`` (ADR-013) with node count up front
    and the served backend annotated by whichever leaf actually ran —
    the trace must show what the request PAID, not what the policy
    intended."""
    with _span("analytics.rollup", nodes=len(view.nodes)):
        return _fleet_stats_dispatch(view, backend)


def _fleet_stats_dispatch(
    view: FleetView, backend: str | None = None
) -> dict[str, Any]:
    if backend == "python":
        return python_fleet_stats(view)
    if backend == "xla":
        if view.provider.name != "tpu":
            raise ValueError(
                "backend='xla' unsupported for provider "
                f"{view.provider.name!r}: the columnar encoding carries "
                "TPU device accessors only"
            )
        return _xla_stats(view)
    if view.provider.name != "tpu":
        return python_fleet_stats(view)
    # The policy lives in chosen_backend — ONE place — so what serves a
    # request and what benches/operators are told always agree.
    try:
        choice = chosen_backend(len(view.nodes))
        if choice == "calibrating":
            if calibration.try_begin_probe():
                try:
                    # Double-check under the lock: a probe that finished
                    # between our chosen_backend read and the acquire
                    # has already recorded fresh timings — re-probing
                    # would break the one-probe-per-window guarantee.
                    if chosen_backend(len(view.nodes)) == "calibrating":
                        stats = _calibrate(view)
                        calibration.record_success()
                        return stats
                finally:
                    calibration.end_probe()
                # Fresh timings exist (someone else probed): fall
                # through to dispatch on the re-read choice below.
                choice = chosen_backend(len(view.nodes))
            else:
                # Another request is mid-probe (first calibration or a
                # TTL-expiry re-probe under concurrent load). Never
                # stack a redundant ~600 ms+ probe; instead serve the
                # STALE measured winner if one exists (TTL re-probe —
                # the old measurement is seconds past its window, not
                # wrong; same policy code as chosen_backend), and only
                # on a first-ever calibration (no measurement at all)
                # fall through to the Python fallback below.
                if calibration.measured_winner(len(view.nodes)) == "xla":
                    stats = _xla_stats(view)
                    calibration.record_success()
                    return stats
                choice = "python"
        if choice == "xla":
            stats = _xla_stats(view)
            calibration.record_success()
            return stats
    except Exception as exc:  # noqa: BLE001 — degraded, never broken
        calibration.record_failure(f"{type(exc).__name__}: {exc}"[:200])
    # Outside the try: a Python-path error must propagate, not be
    # memoized as a broken XLA backend by record_failure.
    return python_fleet_stats(view)


def _calibrate(view: FleetView) -> dict[str, Any]:
    """First at-scale request: measure both backends, record, serve XLA.
    Median of 3 timed samples per backend — a process-lifetime choice
    must not hang off one sample that caught a GC pause or a network
    blip to a tunneled device. Cost over the steady state, paid once per
    process and only at ≥ XLA_ROLLUP_MIN_NODES sizes: one compile
    warm-up + 3 XLA dispatches + 3 Python passes — host-dependent, from
    ~30 ms on a local device to ~600 ms+ over a tunneled one (3×~155 ms
    dispatch, BENCH_r03) plus the compile. Servers running
    --background-sync pay it on the first background tick, off the
    request path; inline-sync servers pay it on the first at-scale page
    view."""
    import statistics

    def timed(fn: Callable[[], Any]) -> float:
        samples = []
        for _ in range(3):
            t0 = time.perf_counter()
            fn()
            samples.append((time.perf_counter() - t0) * 1000)
        return statistics.median(samples)

    # Its own span (ADR-013): the probe is THE latency spike a trace
    # reader hunting a slow first at-scale request needs to see named.
    with _span("analytics.calibrate", nodes=len(view.nodes)):
        stats = _xla_stats(view)  # warm-up: compile for this fleet-shape bucket
        xla_ms = timed(lambda: _xla_stats(view))
        python_ms = timed(lambda: python_fleet_stats(view))
    # One atomic publish after BOTH passes: no concurrent reader can
    # observe a half-published calibration (which would misroute
    # first-calibration losers onto the XLA path and let their
    # dispatches contend with — and inflate — the Python timing pass
    # above) or, on a re-probe, a mix of new python and old xla
    # timings.
    calibration.publish(
        xla_ms=xla_ms,
        python_ms_per_node=python_ms / max(1, len(view.nodes)),
        calibrated_at=time.monotonic(),
    )
    return stats


def _xla_stats(view: FleetView) -> dict[str, Any]:
    from ..runtime.device_cache import fleet_cache, rollup_results
    from .fleet_jax import rollup_to_dict

    _annotate(backend="xla")
    # ADR-020: when the fused rollup+forecast program already computed
    # this snapshot's rollup (same provider, same version), serve the
    # parked host dict — zero device work for this call.
    cached = rollup_results.get(
        view.provider.name, getattr(view, "version", None)
    )
    if cached is not None:
        _annotate(rollup_source="fused")
        cached["generation_counts"] = _generation_counts(view.nodes)
        return cached
    # Versioned views (server snapshots) hit the device-resident cache:
    # a warm request re-uses the columns already living on device and
    # pays dispatch + one coalesced device_get only — the host→device
    # upload that dominated rollup_xla_ms in BENCH_r05 happens once per
    # snapshot version, usually on the background-sync warm. Unversioned
    # views fall through to a fresh host encode inside fleet_for.
    stats = rollup_to_dict(fleet_cache.fleet_for(view))
    # Exact generation names (see _generation_counts): the device-side
    # histogram is fixed-vocabulary; the display histogram is not.
    stats["generation_counts"] = _generation_counts(view.nodes)
    return stats
