"""Windowed-series statistics over the ADR-018 history tier.

``HistoryStore`` hands series out as ``jnp`` arrays; this module is the
analytics-layer consumer — one fused reduction per series (min/max/mean
and a least-squares slope) instead of five Python passes. On a jax-less
host the same numbers come from the pure-Python fallback, so the trends
page degrades gracefully rather than 500ing.
"""

from __future__ import annotations

from typing import Any, Sequence


def _stats_jax(values: Any) -> dict[str, float] | None:
    try:
        import jax.numpy as jnp
    except Exception:  # noqa: BLE001 — fall through to pure Python
        return None
    v = jnp.asarray(values, dtype=jnp.float32)
    n = int(v.shape[0])
    if n == 0:
        return None
    # Slope by least squares on the step index: with x centered,
    # slope = sum(x * (v - mean)) / sum(x^2).
    x = jnp.arange(n, dtype=jnp.float32) - (n - 1) / 2.0
    denom = jnp.sum(x * x)
    slope = jnp.where(denom > 0, jnp.sum(x * (v - jnp.mean(v))) / jnp.maximum(denom, 1.0), 0.0)
    return {
        "n": float(n),
        "latest": float(v[-1]),
        "min": float(jnp.min(v)),
        "max": float(jnp.max(v)),
        "mean": float(jnp.mean(v)),
        "slope_per_step": float(slope),
    }


def series_stats(values: Sequence[float] | Any) -> dict[str, float]:
    """min/max/mean/latest plus a per-step least-squares slope for one
    windowed series. Empty input is a zeroed record, never an error —
    trend pages render during warm-up."""
    out = _stats_jax(values)
    if out is not None:
        return out
    vals = [float(v) for v in values]
    if not vals:
        return {
            "n": 0.0,
            "latest": 0.0,
            "min": 0.0,
            "max": 0.0,
            "mean": 0.0,
            "slope_per_step": 0.0,
        }
    n = len(vals)
    mean = sum(vals) / n
    num = 0.0
    denom = 0.0
    for i, v in enumerate(vals):
        x = i - (n - 1) / 2.0
        num += x * (v - mean)
        denom += x * x
    return {
        "n": float(n),
        "latest": vals[-1],
        "min": min(vals),
        "max": max(vals),
        "mean": mean,
        "slope_per_step": num / denom if denom > 0 else 0.0,
    }
