"""Jitted fleet-rollup kernels.

One XLA program computes every dashboard aggregate in a single fused
pass over the columnar fleet (no Python loops, no data-dependent
control flow — `lax`/`segment_sum` only, per the XLA-semantics rules).
Segment counts are static (padding-row trick from ``encode``), so the
program caches per (node-bucket, pod-bucket) shape pair.

The kernels are pure array→array; the serving path reaches them through
``analytics.stats.fleet_stats`` (called by ``ProviderState.fleet_stats``
and rendered by the overview page), which wraps :func:`rollup_to_dict`
and converts to host ints exactly once, with a pure-Python fallback on
jax-less hosts.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Mapping

import jax
import jax.numpy as jnp

from .encode import GENERATION_IDS, PHASE_IDS, FleetArrays

#: Phase index of 'Running' in the stable vocabulary.
_RUNNING = PHASE_IDS.index("Running")


def local_aggregates(
    node_capacity: jax.Array,
    node_allocatable: jax.Array,
    node_ready: jax.Array,
    node_generation: jax.Array,
    node_valid: jax.Array,
    pod_request: jax.Array,
    pod_phase: jax.Array,
    pod_node_idx: jax.Array,
    pod_valid: jax.Array,
    *,
    n_nodes_pad: int,
    n_generations: int = len(GENERATION_IDS),
    n_phases: int = len(PHASE_IDS),
) -> dict[str, jax.Array]:
    """The shared reduction body: sums/histograms over the rows it is
    given. Single-device rollup calls it on the whole fleet; the
    sharded rollup calls it per shard and psums the outputs — ONE
    definition so the two paths cannot drift (``per_node_in_use``
    segments into the *global* node index space either way;
    ``n_nodes_pad`` is that global size, not the local row count)."""
    cap = node_capacity * node_valid
    alloc = node_allocatable * node_valid
    running = ((pod_phase == _RUNNING) & (pod_valid == 1)).astype(jnp.int32)
    req_running = pod_request * running
    per_node_in_use = jax.ops.segment_sum(
        req_running, pod_node_idx, num_segments=n_nodes_pad + 1
    )[:n_nodes_pad]
    return {
        "capacity": jnp.sum(cap),
        "allocatable": jnp.sum(alloc),
        "in_use": jnp.sum(req_running),
        "nodes_total": jnp.sum(node_valid),
        "nodes_ready": jnp.sum(node_ready * node_valid),
        "phase_counts": jax.ops.segment_sum(
            pod_valid, pod_phase, num_segments=n_phases
        ),
        "generation_counts": jax.ops.segment_sum(
            node_valid, node_generation, num_segments=n_generations
        ),
        "per_node_in_use": per_node_in_use,
    }


def aggregates_to_host_dict(out: Mapping[str, Any], n_nodes: int) -> dict[str, Any]:
    """Shared host-side conversion (one device_get happens in the
    caller): scalars to ints, vocabulary vectors to name→count maps."""
    allocatable = int(out["allocatable"])
    in_use = int(out["in_use"])
    return {
        "capacity": int(out["capacity"]),
        "allocatable": allocatable,
        "in_use": in_use,
        "free": allocatable - in_use,
        "nodes_total": int(out["nodes_total"]),
        "nodes_ready": int(out["nodes_ready"]),
        "phase_counts": {
            name: int(c) for name, c in zip(PHASE_IDS, out["phase_counts"])
        },
        "generation_counts": {
            name: int(c)
            for name, c in zip(GENERATION_IDS, out["generation_counts"])
            if int(c) > 0
        },
        "per_node_in_use": [int(v) for v in out["per_node_in_use"][:n_nodes]],
    }


@partial(jax.jit, static_argnames=("n_generations", "n_phases"))
def fleet_rollup(
    node_capacity: jax.Array,
    node_allocatable: jax.Array,
    node_ready: jax.Array,
    node_generation: jax.Array,
    node_valid: jax.Array,
    pod_request: jax.Array,
    pod_phase: jax.Array,
    pod_node_idx: jax.Array,
    pod_valid: jax.Array,
    *,
    n_generations: int = len(GENERATION_IDS),
    n_phases: int = len(PHASE_IDS),
) -> dict[str, jax.Array]:
    """All fleet aggregates in one fused program.

    Returns device arrays:
    - capacity/allocatable/in_use/free: int32 scalars
    - nodes_total/nodes_ready: int32 scalars
    - phase_counts[n_phases], generation_counts[n_generations]
    - per_node_in_use[N_pad]: chips used by Running pods on each node
    - per_node_util_pct[N_pad]: 0-100 float32, 0 where allocatable=0
    - max_node_util_pct / hot_nodes (util >= 90): fleet pressure signals
    """
    n_nodes_pad = node_capacity.shape[0]
    out = local_aggregates(
        node_capacity,
        node_allocatable,
        node_ready,
        node_generation,
        node_valid,
        pod_request,
        pod_phase,
        pod_node_idx,
        pod_valid,
        n_nodes_pad=n_nodes_pad,
        n_generations=n_generations,
        n_phases=n_phases,
    )
    alloc_f = (node_allocatable * node_valid).astype(jnp.float32)
    util = jnp.where(
        alloc_f > 0,
        out["per_node_in_use"].astype(jnp.float32) / alloc_f * 100.0,
        0.0,
    )
    return {
        **out,
        "free": out["allocatable"] - out["in_use"],
        "per_node_util_pct": util,
        "max_node_util_pct": jnp.max(util),
        "hot_nodes": jnp.sum((util >= 90.0).astype(jnp.int32)),
    }


#: Static cluster-axis segment count for the region rollup (ADR-026).
#: Fixed — not shape-derived — so the program key stays the familiar
#: (node_pad, pod_pad) pair and the ADR-020 bucket table covers region
#: programs with no new dimension. Fleets with more clusters clamp the
#: overflow into the last segment (visible as a "+more" row host-side);
#: 64 federated clusters is far past the ROADMAP's 16k-node target.
REGION_CLUSTER_SEGMENTS = 64


def local_region_aggregates(
    node_capacity: jax.Array,
    node_allocatable: jax.Array,
    node_ready: jax.Array,
    node_valid: jax.Array,
    node_cluster: jax.Array,
    node_slice: jax.Array,
    pod_request: jax.Array,
    pod_phase: jax.Array,
    pod_node_idx: jax.Array,
    pod_valid: jax.Array,
    *,
    n_nodes_pad: int,
    n_clusters: int = REGION_CLUSTER_SEGMENTS,
    cluster_ext: jax.Array | None = None,
    slice_ext: jax.Array | None = None,
) -> dict[str, jax.Array]:
    """Per-region sums for BOTH drill-down levels in one fused pass
    (ADR-026): cluster-level vectors [n_clusters] and slice-level
    vectors [n_nodes_pad] (a slice holds ≥1 node, so the node axis
    bounds the slice count and the program key stays (node_pad,
    pod_pad)). Shared by the single-device ``region_rollup`` and the
    sharded mesh variant — same one-definition discipline as
    :func:`local_aggregates`. Pod rows reach their region through their
    node's ids: the id columns are extended with one sentinel row so
    the encoder's "unscheduled pods point at the padding row" trick
    needs no masking here either (sentinel segments are sliced off)."""
    cluster = jnp.clip(node_cluster, 0, n_clusters - 1) * node_valid
    slc = node_slice * node_valid
    running = ((pod_phase == _RUNNING) & (pod_valid == 1)).astype(jnp.int32)
    pending = (
        (pod_phase == PHASE_IDS.index("Pending")) & (pod_valid == 1)
    ).astype(jnp.int32)
    req_running = pod_request * running
    # Pod → region: index the sentinel-extended id columns by the pod's
    # node row (n_nodes_pad = "no node" → the sentinel segment). The
    # sharded mesh path passes replicated full-fleet ext columns because
    # pod_node_idx is a *global* row index that a local node shard
    # cannot answer; single-device callers leave them None.
    if cluster_ext is None:
        cluster_ext = jnp.concatenate(
            [cluster, jnp.array([n_clusters], dtype=jnp.int32)]
        )
    if slice_ext is None:
        slice_ext = jnp.concatenate(
            [slc, jnp.array([n_nodes_pad], dtype=jnp.int32)]
        )
    pod_cluster = cluster_ext[pod_node_idx]
    pod_slice = slice_ext[pod_node_idx]

    def per_cluster(values: jax.Array) -> jax.Array:
        return jax.ops.segment_sum(values, cluster, num_segments=n_clusters)

    def per_slice(values: jax.Array) -> jax.Array:
        return jax.ops.segment_sum(values, slc, num_segments=n_nodes_pad)

    return {
        "cluster_capacity": per_cluster(node_capacity * node_valid),
        "cluster_allocatable": per_cluster(node_allocatable * node_valid),
        "cluster_nodes": per_cluster(node_valid),
        "cluster_ready": per_cluster(node_ready * node_valid),
        "cluster_in_use": jax.ops.segment_sum(
            req_running, pod_cluster, num_segments=n_clusters + 1
        )[:n_clusters],
        "cluster_pending": jax.ops.segment_sum(
            pending, pod_cluster, num_segments=n_clusters + 1
        )[:n_clusters],
        "slice_capacity": per_slice(node_capacity * node_valid),
        "slice_allocatable": per_slice(node_allocatable * node_valid),
        "slice_nodes": per_slice(node_valid),
        "slice_ready": per_slice(node_ready * node_valid),
        "slice_in_use": jax.ops.segment_sum(
            req_running, pod_slice, num_segments=n_nodes_pad + 1
        )[:n_nodes_pad],
        "slice_pending": jax.ops.segment_sum(
            pending, pod_slice, num_segments=n_nodes_pad + 1
        )[:n_nodes_pad],
    }


@jax.jit
def region_rollup(
    node_capacity: jax.Array,
    node_allocatable: jax.Array,
    node_ready: jax.Array,
    node_valid: jax.Array,
    node_cluster: jax.Array,
    node_slice: jax.Array,
    pod_request: jax.Array,
    pod_phase: jax.Array,
    pod_node_idx: jax.Array,
    pod_valid: jax.Array,
) -> dict[str, jax.Array]:
    """Both drill-down levels of the viewport tree in one fused XLA
    program — the aggregate-before-transfer discipline of ADR-012/020
    applied to navigation: what crosses the device boundary is a few
    region-sized vectors, never 16k node rows."""
    n_nodes_pad = node_capacity.shape[0]
    return local_region_aggregates(
        node_capacity,
        node_allocatable,
        node_ready,
        node_valid,
        node_cluster,
        node_slice,
        pod_request,
        pod_phase,
        pod_node_idx,
        pod_valid,
        n_nodes_pad=n_nodes_pad,
    )


def region_rollup_arrays(
    fleet: FleetArrays, node_cluster: Any, node_slice: Any
) -> dict[str, jax.Array]:
    """Dispatch :func:`region_rollup` through the ADR-020 registry —
    the same ledger-keyed AOT pattern as :func:`rollup_arrays`, under
    the program name ``analytics.region_rollup`` with the identical
    (node_pad, pod_pad) key, so the extended bucket table keeps 4k/16k
    viewport paints compile-free. ``node_cluster``/``node_slice`` are
    the host-built per-node region ids (viewport/tree.py), padded to
    the fleet's node bucket."""
    from ..models.aot import registry as _aot_registry
    from ..obs.jaxcost import track as _jax_track

    cols = (
        jnp.asarray(fleet.node_capacity),
        jnp.asarray(fleet.node_allocatable),
        jnp.asarray(fleet.node_ready),
        jnp.asarray(fleet.node_valid),
        jnp.asarray(node_cluster),
        jnp.asarray(node_slice),
        jnp.asarray(fleet.pod_request),
        jnp.asarray(fleet.pod_phase),
        jnp.asarray(fleet.pod_node_idx),
        jnp.asarray(fleet.pod_valid),
    )
    ledger_key = (
        tuple(fleet.node_capacity.shape), tuple(fleet.pod_request.shape)
    )
    reg = _aot_registry()
    exe = (
        reg.executable("analytics.region_rollup", ledger_key)
        if reg.ready()
        else None
    )
    with _jax_track("analytics.region_rollup", ledger_key):
        if exe is not None:
            try:
                return exe(*cols)
            except Exception as exc:  # noqa: BLE001 — AOT is an optimization
                reg.note_exec_failure(
                    "analytics.region_rollup",
                    f"{type(exc).__name__}: {exc}"[:200],
                )
        return region_rollup(*cols)


def rollup_arrays(fleet: FleetArrays) -> dict[str, jax.Array]:
    from ..models.aot import registry as _aot_registry
    from ..obs.jaxcost import track as _jax_track

    cols = (
        jnp.asarray(fleet.node_capacity),
        jnp.asarray(fleet.node_allocatable),
        jnp.asarray(fleet.node_ready),
        jnp.asarray(fleet.node_generation),
        jnp.asarray(fleet.node_valid),
        jnp.asarray(fleet.pod_request),
        jnp.asarray(fleet.pod_phase),
        jnp.asarray(fleet.pod_node_idx),
        jnp.asarray(fleet.pod_valid),
    )
    # ADR-019 cost ledger: padded column shapes are the recompile key
    # (static args are defaulted constants here). ADR-020: the same key
    # looks up the startup-compiled executable, so a registry hit makes
    # this call a warm dispatch with zero request-path compiles.
    ledger_key = (
        tuple(fleet.node_capacity.shape), tuple(fleet.pod_request.shape)
    )
    reg = _aot_registry()
    exe = (
        reg.executable("analytics.fleet_rollup", ledger_key)
        if reg.ready()
        else None
    )
    with _jax_track("analytics.fleet_rollup", ledger_key):
        if exe is not None:
            try:
                return exe(*cols)
            except Exception as exc:  # noqa: BLE001 — AOT is an optimization
                reg.note_exec_failure(
                    "analytics.fleet_rollup",
                    f"{type(exc).__name__}: {exc}"[:200],
                )
        return fleet_rollup(*cols)


def rollup_to_dict(fleet: FleetArrays) -> dict[str, Any]:
    """Host-side view of the rollup: scalars as ints, vocabulary vectors
    as name→count mappings — the shape ``allocation_summary`` and
    ``count_pod_phases`` produce, so pages can swap implementations.

    The whole result dict is materialized with ONE ``device_get``:
    converting elements piecemeal issues a separate device→host
    transfer per scalar (hundreds for the per-node vector), which over
    a tunneled/remote TPU turns a sub-millisecond rollup into tens of
    seconds. The fetch goes through the runtime transfer funnel: inside
    a request's TransferBatch it coalesces with every other pending
    stage (forecast, mesh shards) into one round-trip; standalone it is
    the same single counted device_get as before."""
    from ..runtime import transfer

    out = transfer.fetch(rollup_arrays(fleet))
    return rollup_host_view(out, fleet.n_nodes)


def rollup_host_view(out: Mapping[str, Any], n_nodes: int) -> dict[str, Any]:
    """Finalize an ALREADY-FETCHED rollup tree into the serving dict —
    shared by :func:`rollup_to_dict` and the fused rollup+forecast path
    (ADR-020), which fetches the rollup together with the forecast in
    one coalesced device_get and must produce the identical key set."""
    result = aggregates_to_host_dict(out, n_nodes)
    result.update(
        {
            "utilization_pct": (
                round(result["in_use"] / result["capacity"] * 100)
                if result["capacity"] > 0
                else 0
            ),
            "max_node_util_pct": float(out["max_node_util_pct"]),
            "hot_nodes": int(out["hot_nodes"]),
        }
    )
    return result


def validate_rollup(fleet: FleetArrays, summary: Mapping[str, int]) -> bool:
    """Cross-check the XLA rollup against a pure-Python summary (used in
    tests to pin the two implementations together)."""
    rolled = rollup_to_dict(fleet)
    return all(rolled[k] == summary[k] for k in ("capacity", "allocatable", "in_use", "free"))
