"""Analytics — columnar fleet encoding and JAX rollup kernels.

The reference re-derives fleet aggregates with per-render JS loops over
pod/node objects (`/root/reference/src/components/OverviewPage.tsx:78-130`
— fine at tens of nodes). This framework's fleet-scale path is
TPU-native instead: snapshots encode once into fixed-shape columnar
arrays (``encode``) and every aggregate the pages need — allocation,
phase histograms, per-generation counts, per-node utilization — comes
out of one fused, jitted XLA program (``fleet_jax``), optionally sharded
over a device mesh for multi-host fleets (``parallel.mesh``).
"""

from .encode import FleetArrays, GENERATION_IDS, PHASE_IDS, encode_fleet
from .fleet_jax import fleet_rollup, rollup_to_dict
from .trends import series_stats

__all__ = [
    "FleetArrays",
    "GENERATION_IDS",
    "PHASE_IDS",
    "encode_fleet",
    "fleet_rollup",
    "rollup_to_dict",
    "series_stats",
]
