"""Snapshot → columnar arrays.

Pure numpy (no jax import at encode time — encoding happens host-side
once per sync); fixed categorical vocabularies so array values are
stable across fleets and the jitted kernels never see strings.

Shapes are padded to the next power-of-two bucket by default: XLA
compiles one program per shape, so padding turns "recompile every time
a pod appears" into a handful of cached compilations
(`/opt/skills/guides/pallas_guide.md` static-shape discipline).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Iterable, Sequence

import numpy as np

from ..domain import objects as obj
from ..domain import tpu

#: Stable generation vocabulary (index = id). 'other' absorbs future
#: generations so encoding is total.
GENERATION_IDS: tuple[str, ...] = ("v4", "v5e", "v5p", "v6e", "unknown", "other")

#: Stable pod-phase vocabulary, mirroring count_pod_phases' buckets.
PHASE_IDS: tuple[str, ...] = ("Running", "Pending", "Succeeded", "Failed", "Other")


def _gen_id(generation: str) -> int:
    try:
        return GENERATION_IDS.index(generation)
    except ValueError:
        return GENERATION_IDS.index("other")


def _phase_id(phase: str) -> int:
    try:
        return PHASE_IDS.index(phase)
    except ValueError:
        return PHASE_IDS.index("Other")


def _bucket(n: int, minimum: int = 8) -> int:
    size = minimum
    while size < n:
        size *= 2
    return size


@dataclass
class FleetArrays:
    """Fixed-shape columnar fleet. ``n_nodes``/``n_pods`` are the live
    counts; rows beyond them are zero padding with valid=0."""

    n_nodes: int
    n_pods: int
    # Node columns [N_pad]
    node_capacity: np.ndarray
    node_allocatable: np.ndarray
    node_ready: np.ndarray
    node_generation: np.ndarray
    node_valid: np.ndarray
    # Pod columns [P_pad]
    pod_request: np.ndarray
    pod_phase: np.ndarray
    pod_node_idx: np.ndarray  # index into node rows; n_nodes_pad = "no node"
    pod_valid: np.ndarray
    node_names: list[str]

    @property
    def n_nodes_padded(self) -> int:
        return int(self.node_capacity.shape[0])

    @property
    def n_pods_padded(self) -> int:
        return int(self.pod_request.shape[0])


def encode_fleet(
    nodes: Sequence[Any],
    pods: Iterable[Any],
    *,
    pad: bool = True,
) -> FleetArrays:
    """Encode a provider view (TPU nodes + TPU-requesting pods) into
    columnar arrays. Unscheduled pods point at the padding node row, so
    segment-sums need no masking beyond ``pod_valid``."""
    node_list = list(nodes)
    pod_list = list(pods)
    n_nodes, n_pods = len(node_list), len(pod_list)
    np_nodes = _bucket(max(n_nodes, 1)) if pad else max(n_nodes, 1)
    np_pods = _bucket(max(n_pods, 1)) if pad else max(n_pods, 1)

    node_capacity = np.zeros(np_nodes, dtype=np.int32)
    node_allocatable = np.zeros(np_nodes, dtype=np.int32)
    node_ready = np.zeros(np_nodes, dtype=np.int32)
    node_generation = np.zeros(np_nodes, dtype=np.int32)
    node_valid = np.zeros(np_nodes, dtype=np.int32)
    node_names: list[str] = []
    index_of: dict[str, int] = {}
    for i, node in enumerate(node_list):
        node_capacity[i] = tpu.get_node_chip_capacity(node)
        node_allocatable[i] = tpu.get_node_chip_allocatable(node)
        node_ready[i] = 1 if obj.is_node_ready(node) else 0
        node_generation[i] = _gen_id(tpu.get_node_generation(node))
        node_valid[i] = 1
        name = obj.name(node)
        node_names.append(name)
        index_of[name] = i

    pod_request = np.zeros(np_pods, dtype=np.int32)
    pod_phase = np.zeros(np_pods, dtype=np.int32)
    pod_node_idx = np.full(np_pods, np_nodes, dtype=np.int32)
    pod_valid = np.zeros(np_pods, dtype=np.int32)
    for j, pod in enumerate(pod_list):
        pod_request[j] = tpu.get_pod_chip_request(pod)
        pod_phase[j] = _phase_id(obj.pod_phase(pod))
        node_name = obj.pod_node_name(pod)
        if node_name and node_name in index_of:
            pod_node_idx[j] = index_of[node_name]
        pod_valid[j] = 1

    return FleetArrays(
        n_nodes=n_nodes,
        n_pods=n_pods,
        node_capacity=node_capacity,
        node_allocatable=node_allocatable,
        node_ready=node_ready,
        node_generation=node_generation,
        node_valid=node_valid,
        pod_request=pod_request,
        pod_phase=pod_phase,
        pod_node_idx=pod_node_idx,
        pod_valid=pod_valid,
        node_names=node_names,
    )
