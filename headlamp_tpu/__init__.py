"""headlamp_tpu — TPU-native Kubernetes accelerator-visibility framework.

A ground-up rebuild of the capabilities of the Intel GPU Headlamp plugin
(`/root/reference`, see SURVEY.md) around Google Cloud TPU primitives:

- ``domain``       — pure domain model: GKE TPU node/pod detection, chip
                     accounting, formatters; Intel GPU as a second
                     provider behind a provider-agnostic abstraction.
- ``topology``     — ICI pod-slice modeling: topology parsing, slice
                     grouping, host/chip mesh coordinates, torus links.
- ``fleet``        — fixture generators for the BASELINE configs.
- ``transport``    — the ApiProxy contract: KubeTransport (urllib) and
                     MockTransport, hard per-request timeouts.
- ``context``      — AcceleratorDataContext: dual-track fetching,
                     per-provider fallback chains and degradation.
- ``metrics``      — Prometheus client: discovery chain, parallel
                     PromQL fan-out, schema-tolerant series resolution,
                     range-query utilization history.
- ``ui``           — element tree + CommonComponents kit, HTML/text
                     renderers.
- ``pages``        — Overview/Nodes/Pods/DevicePlugins/Metrics plus the
                     TopologyPage ICI mesh view.
- ``integrations`` — Node/Pod detail sections, Nodes-table columns.
- ``registration`` — the plugin surface (sidebar/routes/sections/columns).
- ``server``       — standalone dashboard host (demo/apiserver/in-cluster).
- ``analytics``    — columnar fleet encoding + jitted XLA rollups.
- ``parallel``     — device meshes, shard_map rollup with psum.
- ``models``       — utilization forecaster (bf16 MLP, fused online fit).
"""

__version__ = "0.1.0"
