"""headlamp_tpu — TPU-native Kubernetes accelerator-visibility framework.

A ground-up rebuild of the capabilities of the Intel GPU Headlamp plugin
(`/root/reference`, see SURVEY.md) around Google Cloud TPU primitives:

- ``domain``    — pure domain model: GKE TPU node/pod detection, chip
                  accounting, formatters; Intel GPU as a second provider
                  behind a provider-agnostic accelerator abstraction.
- ``topology``  — ICI pod-slice modeling: topology parsing, slice grouping,
                  host/chip mesh coordinates and torus links (the data the
                  TopologyPage renders).
- ``fleet``     — fixture generators for the BASELINE configs (v5e-4,
                  v5p-32 multi-host, mixed Intel+TPU, 1024-node stress).
Landing later this round (see SURVEY.md §7 build order):
``metrics`` (mini-PromQL evaluator + TPU metrics-client mirror),
``analytics`` (JAX columnar fleet rollups measured by bench.py),
``models``/``parallel`` (telemetry-forecasting model with a mesh-sharded
train step), and the sibling ``plugin/`` Headlamp frontend (TS/React)
whose pure logic this package mirrors 1:1 via shared JSON fixtures.
"""

__version__ = "0.1.0"
