"""Mesh construction and sharded fleet rollup.

Design follows the scaling-book recipe: pick a mesh, annotate shardings,
let XLA insert the collectives. The fleet rollup is embarrassingly
row-parallel (nodes/pods partition over hosts; aggregates reduce), so
it runs under ``shard_map`` with explicit ``psum`` over the ``hosts``
axis — the ICI-friendly pattern (one all-reduce of a few scalars and
two small histograms; per-node vectors all-gather only at the end).
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
try:
    from jax import shard_map  # jax >= 0.7 stable API
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..analytics.encode import FleetArrays
from ..analytics.fleet_jax import aggregates_to_host_dict, local_aggregates


def fleet_mesh(n_devices: int | None = None) -> Mesh:
    """1-D ``hosts`` mesh over the first ``n_devices`` devices — fleet
    rows are the only sharded dimension in analytics."""
    import numpy as np

    devices = jax.devices()
    n = n_devices or len(devices)
    return Mesh(np.array(devices[:n]).reshape(n), axis_names=("hosts",))


def train_mesh(n_devices: int | None = None) -> Mesh:
    """2-D ``(data, model)`` mesh for the forecaster train step: batch
    shards over ``data``, hidden dimension over ``model`` (dp × tp)."""
    import numpy as np

    devices = jax.devices()
    n = n_devices or len(devices)
    model = 2 if n % 2 == 0 and n >= 2 else 1
    data = n // model
    grid = np.array(devices[: data * model]).reshape(data, model)
    return Mesh(grid, axis_names=("data", "model"))


def _pad_to_multiple(a: jnp.ndarray, multiple: int, fill: int = 0) -> jnp.ndarray:
    rem = a.shape[0] % multiple
    if rem == 0:
        return a
    pad = multiple - rem
    return jnp.concatenate([a, jnp.full((pad,), fill, a.dtype)])


def sharded_rollup(fleet: FleetArrays, mesh: Mesh) -> dict[str, Any]:
    """Fleet rollup partitioned over the ``hosts`` axis.

    Each shard reduces its local node/pod rows; cross-host reduction is
    a single ``psum`` per aggregate. The per-node in-use vector is
    computed as a local segment-sum into the *global* node index space
    then psum-reduced — pods and their nodes may land on different
    shards, which plain concatenation would miscount.
    """
    n_hosts = mesh.shape["hosts"]

    node_cols = [
        jnp.asarray(fleet.node_capacity),
        jnp.asarray(fleet.node_allocatable),
        jnp.asarray(fleet.node_ready),
        jnp.asarray(fleet.node_generation),
        jnp.asarray(fleet.node_valid),
    ]
    pod_cols = [
        jnp.asarray(fleet.pod_request),
        jnp.asarray(fleet.pod_phase),
        jnp.asarray(fleet.pod_node_idx),
        jnp.asarray(fleet.pod_valid),
    ]
    node_cols = [_pad_to_multiple(c, n_hosts) for c in node_cols]
    pod_cols = [_pad_to_multiple(c, n_hosts) for c in pod_cols]
    n_nodes_pad = int(node_cols[0].shape[0])

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P("hosts"),) * 5 + (P("hosts"),) * 4,
        out_specs=P(),  # fully replicated aggregates (every out is a psum)
    )
    def rollup_shard(cap, alloc, ready, gen, nvalid, req, phase, nidx, pvalid):
        # One shared reduction body with the single-device rollup
        # (fleet_jax.local_aggregates) — pod_node_idx already indexes
        # the GLOBAL node space, so each shard's segment-sum lands in
        # the right global rows and a psum completes every aggregate.
        local = local_aggregates(
            cap, alloc, ready, gen, nvalid, req, phase, nidx, pvalid,
            n_nodes_pad=n_nodes_pad,
        )
        return {k: jax.lax.psum(v, "hosts") for k, v in local.items()}

    with mesh:
        out = jax.device_get(rollup_shard(*node_cols, *pod_cols))
    result = aggregates_to_host_dict(out, fleet.n_nodes)
    return result


def shard_fleet_arrays(fleet: FleetArrays, mesh: Mesh) -> dict[str, jax.Array]:
    """Device-put the columnar fleet with row shardings over ``hosts`` —
    for callers composing their own sharded computations."""
    spec = NamedSharding(mesh, P("hosts"))
    n_hosts = mesh.shape["hosts"]
    cols = {
        "node_capacity": fleet.node_capacity,
        "node_allocatable": fleet.node_allocatable,
        "node_ready": fleet.node_ready,
        "node_generation": fleet.node_generation,
        "node_valid": fleet.node_valid,
        "pod_request": fleet.pod_request,
        "pod_phase": fleet.pod_phase,
        "pod_node_idx": fleet.pod_node_idx,
        "pod_valid": fleet.pod_valid,
    }
    return {
        k: jax.device_put(_pad_to_multiple(jnp.asarray(v), n_hosts), spec)
        for k, v in cols.items()
    }
