"""Mesh construction and sharded fleet rollup.

Design follows the scaling-book recipe: pick a mesh, annotate shardings,
let XLA insert the collectives. The fleet rollup is embarrassingly
row-parallel (nodes/pods partition over hosts; aggregates reduce), so
it runs under ``shard_map`` with explicit ``psum`` over the ``hosts``
axis — the ICI-friendly pattern (one all-reduce of a few scalars and
two small histograms; per-node vectors all-gather only at the end).
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
try:
    from jax import shard_map  # jax >= 0.7 stable API
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..analytics.encode import FleetArrays
from ..analytics.fleet_jax import (
    REGION_CLUSTER_SEGMENTS,
    aggregates_to_host_dict,
    local_aggregates,
    local_region_aggregates,
)
from ..obs.trace import span as _span
from ..runtime import transfer


def _mesh_1d(axis_name: str, n_devices: int | None) -> Mesh:
    import numpy as np

    devices = jax.devices()
    n = n_devices or len(devices)
    return Mesh(np.array(devices[:n]).reshape(n), axis_names=(axis_name,))


def fleet_mesh(n_devices: int | None = None) -> Mesh:
    """1-D ``hosts`` mesh over the first ``n_devices`` devices — fleet
    rows are the only sharded dimension in analytics."""
    return _mesh_1d("hosts", n_devices)


def train_mesh(n_devices: int | None = None) -> Mesh:
    """2-D ``(data, model)`` mesh for the forecaster train step: batch
    shards over ``data``, hidden dimension over ``model`` (dp × tp)."""
    import numpy as np

    devices = jax.devices()
    n = n_devices or len(devices)
    model = 2 if n % 2 == 0 and n >= 2 else 1
    data = n // model
    grid = np.array(devices[: data * model]).reshape(data, model)
    return Mesh(grid, axis_names=("data", "model"))


def _pad_to_multiple(a: jnp.ndarray, multiple: int, fill: int = 0) -> jnp.ndarray:
    rem = a.shape[0] % multiple
    if rem == 0:
        return a
    pad = multiple - rem
    return jnp.concatenate([a, jnp.full((pad,), fill, a.dtype)])


def build_rollup_shard(mesh: Mesh, reducer: str, n_nodes_pad: int) -> Any:
    """The shard_mapped rollup callable for ``mesh``: per-shard
    local_aggregates with the cross-host reduction chosen by ``reducer``
    ("psum" | "ring"). Extracted so the serving path and the AOT
    registry (ADR-020) lower THE SAME body — ``n_nodes_pad`` is the
    global padded node-row count the segment-sums index into."""
    n_hosts = mesh.shape["hosts"]

    def rollup_body(
        cap: jax.Array,
        alloc: jax.Array,
        ready: jax.Array,
        gen: jax.Array,
        nvalid: jax.Array,
        req: jax.Array,
        phase: jax.Array,
        nidx: jax.Array,
        pvalid: jax.Array,
    ) -> dict[str, jax.Array]:
        # One shared reduction body with the single-device rollup
        # (fleet_jax.local_aggregates) — pod_node_idx already indexes
        # the GLOBAL node space, so each shard's segment-sum lands in
        # the right global rows and one all-reduce completes every
        # aggregate.
        local = local_aggregates(
            cap, alloc, ready, gen, nvalid, req, phase, nidx, pvalid,
            n_nodes_pad=n_nodes_pad,
        )
        if reducer == "ring":
            return {
                k: ring_allreduce(v, "hosts", n_hosts) for k, v in local.items()
            }
        return {k: jax.lax.psum(v, "hosts") for k, v in local.items()}

    specs = dict(
        mesh=mesh,
        in_specs=(P("hosts"),) * 5 + (P("hosts"),) * 4,
        out_specs=P(),  # fully replicated aggregates
    )
    # The ring's replicated-in-value output can't be statically inferred.
    return (
        shard_map_unchecked(rollup_body, **specs)
        if reducer == "ring"
        else shard_map(rollup_body, **specs)
    )


def build_region_rollup_shard(mesh: Mesh, reducer: str, n_nodes_pad: int) -> Any:
    """Sharded twin of the viewport region rollup (ADR-026): per-shard
    :func:`local_region_aggregates` + one cross-host reduction per
    region vector — the same one-definition discipline as
    :func:`build_rollup_shard`. The two extra replicated inputs are the
    sentinel-extended region-id columns: ``pod_node_idx`` is a *global*
    row index, so the pod→region gather needs the full-fleet id columns
    on every shard (a few KB, replicated), while node columns stay
    row-sharded."""
    n_hosts = mesh.shape["hosts"]

    def region_body(
        cap: jax.Array,
        alloc: jax.Array,
        ready: jax.Array,
        nvalid: jax.Array,
        cluster: jax.Array,
        slc: jax.Array,
        cluster_ext: jax.Array,
        slice_ext: jax.Array,
        req: jax.Array,
        phase: jax.Array,
        nidx: jax.Array,
        pvalid: jax.Array,
    ) -> dict[str, jax.Array]:
        local = local_region_aggregates(
            cap, alloc, ready, nvalid, cluster, slc,
            req, phase, nidx, pvalid,
            n_nodes_pad=n_nodes_pad,
            cluster_ext=cluster_ext,
            slice_ext=slice_ext,
        )
        if reducer == "ring":
            return {
                k: ring_allreduce(v, "hosts", n_hosts) for k, v in local.items()
            }
        return {k: jax.lax.psum(v, "hosts") for k, v in local.items()}

    specs = dict(
        mesh=mesh,
        in_specs=(P("hosts"),) * 6 + (P(),) * 2 + (P("hosts"),) * 4,
        out_specs=P(),  # fully replicated region vectors
    )
    return (
        shard_map_unchecked(region_body, **specs)
        if reducer == "ring"
        else shard_map(region_body, **specs)
    )


def region_sharded_rollup(
    fleet: FleetArrays,
    node_cluster: Any,
    node_slice: Any,
    mesh: Mesh,
    reducer: str = "psum",
) -> dict[str, Any]:
    """Viewport region rollup partitioned over ``hosts`` — column
    assembly, padding, sentinel-extended id columns, and the AOT/ledger
    dispatch under ``mesh.region_rollup``. Returns the fetched host dict
    (same keys as :func:`~..analytics.fleet_jax.region_rollup`); slice
    vectors are full ``[n_nodes_pad]`` — callers slice to the real
    region count, exactly as with the single-device program."""
    n_hosts = mesh.shape["hosts"]

    node_cols = [
        jnp.asarray(fleet.node_capacity),
        jnp.asarray(fleet.node_allocatable),
        jnp.asarray(fleet.node_ready),
        jnp.asarray(fleet.node_valid),
        jnp.asarray(node_cluster),
        jnp.asarray(node_slice),
    ]
    pod_cols = [
        jnp.asarray(fleet.pod_request),
        jnp.asarray(fleet.pod_phase),
        jnp.asarray(fleet.pod_node_idx),
        jnp.asarray(fleet.pod_valid),
    ]
    # The sentinel-extended id columns are built from the UNPADDED
    # masked ids: the encoder parks unscheduled pods at row np_nodes, so
    # every index from there through the host-padded tail must resolve
    # to the sentinel segment, not to whatever cluster id 0 the padding
    # fill would alias.
    masked_cluster = (
        jnp.clip(node_cols[4], 0, REGION_CLUSTER_SEGMENTS - 1) * node_cols[3]
    )
    masked_slice = node_cols[5] * node_cols[3]
    node_cols = [_pad_to_multiple(c, n_hosts) for c in node_cols]
    pod_cols = [_pad_to_multiple(c, n_hosts) for c in pod_cols]
    n_nodes_pad = int(node_cols[0].shape[0])
    tail = n_nodes_pad + 1 - int(masked_cluster.shape[0])
    cluster_ext = jnp.concatenate(
        [masked_cluster,
         jnp.full((tail,), REGION_CLUSTER_SEGMENTS, dtype=jnp.int32)]
    )
    slice_ext = jnp.concatenate(
        [masked_slice, jnp.full((tail,), n_nodes_pad, dtype=jnp.int32)]
    )

    region_shard = build_region_rollup_shard(mesh, reducer, n_nodes_pad)
    args = (*node_cols, cluster_ext, slice_ext, *pod_cols)
    with mesh:
        with _span(
            "mesh.region_rollup", reducer=reducer, hosts=mesh.devices.size
        ):
            from ..models.aot import registry as _aot_registry
            from ..obs.jaxcost import track as _jax_track

            ledger_key = (
                reducer,
                tuple(mesh.devices.shape),
                tuple(node_cols[0].shape),
                tuple(pod_cols[0].shape),
            )
            reg = _aot_registry()
            exe = (
                reg.executable("mesh.region_rollup", ledger_key)
                if reg.ready()
                else None
            )
            with _jax_track("mesh.region_rollup", ledger_key):
                if exe is not None:
                    try:
                        dispatched = exe(*args)
                    except Exception as exc:  # noqa: BLE001 — AOT is an optimization
                        reg.note_exec_failure(
                            "mesh.region_rollup",
                            f"{type(exc).__name__}: {exc}"[:200],
                        )
                        dispatched = region_shard(*args)
                else:
                    dispatched = region_shard(*args)
            out = transfer.fetch(dispatched)
    return dict(out)


def _rollup_with_reducer(
    fleet: FleetArrays, mesh: Mesh, reducer: str
) -> dict[str, Any]:
    """Shared body of the sharded rollups: column assembly + padding +
    the :func:`build_rollup_shard` program. One definition so the two
    reduction schedules can never drift on what they reduce."""
    n_hosts = mesh.shape["hosts"]

    node_cols = [
        jnp.asarray(fleet.node_capacity),
        jnp.asarray(fleet.node_allocatable),
        jnp.asarray(fleet.node_ready),
        jnp.asarray(fleet.node_generation),
        jnp.asarray(fleet.node_valid),
    ]
    pod_cols = [
        jnp.asarray(fleet.pod_request),
        jnp.asarray(fleet.pod_phase),
        jnp.asarray(fleet.pod_node_idx),
        jnp.asarray(fleet.pod_valid),
    ]
    node_cols = [_pad_to_multiple(c, n_hosts) for c in node_cols]
    pod_cols = [_pad_to_multiple(c, n_hosts) for c in pod_cols]
    n_nodes_pad = int(node_cols[0].shape[0])

    rollup_shard = build_rollup_shard(mesh, reducer, n_nodes_pad)
    with mesh:
        # Funnel fetch: coalesces with the request's other pending
        # device reads when a TransferBatch is active, and is the same
        # single counted device_get standalone.
        with _span(
            "mesh.rollup", reducer=reducer, hosts=mesh.devices.size
        ):
            from ..models.aot import registry as _aot_registry
            from ..obs.jaxcost import track as _jax_track

            # ADR-019 cost ledger: mesh shape + padded columns are the
            # recompile key; the blocking fetch stays OUTSIDE the track
            # so dispatch time is not conflated with transfer time.
            # ADR-020: the key doubles as the AOT registry lookup — a
            # hit serves the startup-compiled executable (the ledger
            # then classifies this call as a warm dispatch).
            ledger_key = (
                reducer,
                tuple(mesh.devices.shape),
                tuple(node_cols[0].shape),
                tuple(pod_cols[0].shape),
            )
            reg = _aot_registry()
            exe = (
                reg.executable("mesh.rollup", ledger_key)
                if reg.ready()
                else None
            )
            with _jax_track("mesh.rollup", ledger_key):
                if exe is not None:
                    try:
                        dispatched = exe(*node_cols, *pod_cols)
                    except Exception as exc:  # noqa: BLE001 — AOT is an optimization
                        reg.note_exec_failure(
                            "mesh.rollup", f"{type(exc).__name__}: {exc}"[:200]
                        )
                        dispatched = rollup_shard(*node_cols, *pod_cols)
                else:
                    dispatched = rollup_shard(*node_cols, *pod_cols)
            out = transfer.fetch(dispatched)
    return aggregates_to_host_dict(out, fleet.n_nodes)


def sharded_rollup(fleet: FleetArrays, mesh: Mesh) -> dict[str, Any]:
    """Fleet rollup partitioned over the ``hosts`` axis.

    Each shard reduces its local node/pod rows; cross-host reduction is
    a single ``psum`` per aggregate. The per-node in-use vector is
    computed as a local segment-sum into the *global* node index space
    then psum-reduced — pods and their nodes may land on different
    shards, which plain concatenation would miscount.
    """
    return _rollup_with_reducer(fleet, mesh, "psum")


def seq_mesh(n_devices: int | None = None) -> Mesh:
    """1-D ``seq`` mesh: the time dimension of telemetry traces is the
    sharded axis (sequence/context parallelism)."""
    return _mesh_1d("seq", n_devices)


def shard_map_unchecked(
    fn: Any, *, mesh: Any, in_specs: Any, out_specs: Any
) -> Any:
    """shard_map with the static replication check off: ppermute-ring
    outputs ARE replicated in value, but the checker can't infer it
    (only psum-style collectives register as replicating). Kwarg name
    varies across jax versions."""
    try:
        return shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    except TypeError:  # older jax
        return shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
        )


def ring_allreduce(x: jax.Array, axis_name: str, axis_size: int) -> jax.Array:
    """All-reduce as ``axis_size - 1`` explicit ``ppermute`` hops around
    a ring — the neighbor-only pattern ``psum`` lowers to on ICI torus
    links, written out so the communication schedule is explicit and
    testable. Call inside ``shard_map``.

    Schedule: a systolic rotation — each hop forwards the ORIGINAL shard
    contribution it most recently received (``buf``) to the right
    neighbor, while ``acc`` sums arrivals locally and is never
    transmitted. After S-1 hops every shard has seen (and summed) every
    contribution. A bandwidth-optimal reduce-scatter ring would send
    partial sums instead; for the few scalars and small histograms
    reduced here the rotation's simplicity wins, and what is on the wire
    per hop is exactly one shard's original contribution."""
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def body(_: jax.Array, carry: tuple[jax.Array, jax.Array]) -> tuple[jax.Array, jax.Array]:
        acc, buf = carry
        buf = jax.lax.ppermute(buf, axis_name, perm)
        return acc + buf, buf

    acc, _ = jax.lax.fori_loop(0, axis_size - 1, body, (x, x))
    return acc


def ring_rollup(fleet: FleetArrays, mesh: Mesh) -> dict[str, Any]:
    """:func:`sharded_rollup` with the cross-host reduction carried by
    :func:`ring_allreduce` instead of ``psum`` — same numbers (pinned by
    tests against the Python oracle), explicit ring schedule."""
    return _rollup_with_reducer(fleet, mesh, "ring")


def alltoall_generation_histogram(fleet: FleetArrays, mesh: Mesh) -> "np.ndarray":  # noqa: F821
    """Generation histogram via ``lax.all_to_all`` bucket regrouping —
    the MoE-router/expert-parallel communication pattern on fleet data.

    Rows arrive host-sharded (each shard holds a slice of the node
    columns); generations are the "experts". Each shard builds its
    LOCAL per-generation partial histogram, splits it into per-owner
    bucket chunks, and one ``all_to_all`` transposes ownership: shard
    *b* receives every peer's partials for the buckets it owns, sums
    them locally (its buckets are now complete), and a tiled
    ``all_gather`` republishes the full histogram. Communication per
    shard is one vocab-sized vector each way — the same volume a psum
    of the full histogram moves, but the reduction lands distributed
    (each shard finalizes only its own buckets), which is the shape
    that scales when the bucket space is large.

    Returns the ``[len(GENERATION_IDS)]`` histogram, pinned by tests to
    both the psum path and the Python oracle."""
    from ..analytics.encode import GENERATION_IDS

    n_hosts = mesh.shape["hosts"]
    vocab = len(GENERATION_IDS)
    # Bucket space padded so every shard owns an equal chunk.
    vocab_pad = ((vocab + n_hosts - 1) // n_hosts) * n_hosts
    chunk = vocab_pad // n_hosts

    gen = _pad_to_multiple(jnp.asarray(fleet.node_generation), n_hosts)
    valid = _pad_to_multiple(jnp.asarray(fleet.node_valid), n_hosts)

    def shard_fn(gen_block: jax.Array, valid_block: jax.Array) -> jax.Array:
        # Local partial histogram over the FULL bucket space — the same
        # segment_sum idiom fleet_jax uses (O(rows), no [rows, vocab]
        # one-hot materialization).
        local = jax.ops.segment_sum(
            (valid_block > 0).astype(jnp.int32), gen_block, num_segments=vocab_pad
        )  # [vocab_pad]
        # Regroup: chunk c of my partials belongs to shard c.
        outgoing = local.reshape(n_hosts, chunk)
        arrived = jax.lax.all_to_all(
            outgoing, "hosts", split_axis=0, concat_axis=0
        )  # [n_hosts, chunk]: every peer's partials for MY buckets
        mine = arrived.sum(axis=0)  # my buckets, complete
        return jax.lax.all_gather(mine, "hosts", tiled=True)  # [vocab_pad]

    with mesh:
        # all_gather-tiled output is replicated-by-construction, which
        # the static checker can't infer (same as the ring reducer).
        full = shard_map_unchecked(
            shard_fn,
            mesh=mesh,
            in_specs=(P("hosts"), P("hosts")),
            out_specs=P(),
        )(gen, valid)
    return transfer.fetch(full)[:vocab]


def sharded_make_windows(
    series: jax.Array, window: int, horizon: int, mesh: Mesh
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Sequence-parallel sliding windows with halo exchange — the
    long-context primitive: traces shard over TIME (the ``seq`` axis),
    and each shard fetches only the ``window + horizon - 1`` boundary
    samples it needs from its right neighbor via one ``ppermute`` (a
    halo exchange riding one ICI hop), never the whole series.

    Returns global ``(x, y, valid)``: x ``[n_series, T, window]``,
    y ``[n_series, T, horizon]``, valid ``[T]`` bool — position p valid
    iff a full window+horizon fits before the end of the trace
    (``p <= T - window - horizon``; the wrap-around halo the last shard
    receives is masked out). Masked rows match
    ``models.make_windows(series, window, horizon)`` exactly (pinned by
    tests). T must divide by the mesh's ``seq`` size."""
    n_series, total_t = series.shape
    s = mesh.shape["seq"]
    if total_t % s != 0:
        raise ValueError(
            f"series length {total_t} must be divisible by seq={s}: pad or "
            "trim the trace to a multiple of the mesh size"
        )
    local_t = total_t // s
    halo = window + horizon - 1
    if halo > local_t:
        raise ValueError(
            f"halo {halo} exceeds the per-shard span {local_t}: use fewer "
            "seq shards or longer traces"
        )

    # Shard i must receive shard (i+1)'s head: send left around the ring.
    perm = [(j, (j - 1) % s) for j in range(s)]

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(None, "seq"),),
        out_specs=(P(None, "seq", None), P(None, "seq", None), P("seq")),
    )
    def windows_shard(block: jax.Array) -> tuple[jax.Array, jax.Array, jax.Array]:
        # block: [n_series, local_t]
        head = block[:, :halo]
        halo_block = jax.lax.ppermute(head, "seq", perm)
        extended = jnp.concatenate([block, halo_block], axis=1)
        starts = jnp.arange(local_t)
        x_idx = starts[:, None] + jnp.arange(window)[None, :]
        y_idx = starts[:, None] + window + jnp.arange(horizon)[None, :]
        x = extended[:, x_idx]          # [n_series, local_t, window]
        y = extended[:, y_idx]          # [n_series, local_t, horizon]
        shard_i = jax.lax.axis_index("seq")
        global_start = shard_i * local_t + starts
        valid = global_start <= total_t - window - horizon
        return x, y, valid

    with mesh:
        return windows_shard(series)


def shard_fleet_arrays(fleet: FleetArrays, mesh: Mesh) -> dict[str, jax.Array]:
    """Device-put the columnar fleet with row shardings over ``hosts`` —
    for callers composing their own sharded computations."""
    spec = NamedSharding(mesh, P("hosts"))
    n_hosts = mesh.shape["hosts"]
    cols = {
        "node_capacity": fleet.node_capacity,
        "node_allocatable": fleet.node_allocatable,
        "node_ready": fleet.node_ready,
        "node_generation": fleet.node_generation,
        "node_valid": fleet.node_valid,
        "pod_request": fleet.pod_request,
        "pod_phase": fleet.pod_phase,
        "pod_node_idx": fleet.pod_node_idx,
        "pod_valid": fleet.pod_valid,
    }
    return {
        k: jax.device_put(_pad_to_multiple(jnp.asarray(v), n_hosts), spec)
        for k, v in cols.items()
    }
