"""Parallelism — device meshes and sharded fleet analytics.

The reference has no distributed compute (SURVEY.md §2.3); the TPU
framework's distributed surface is SPMD analytics and model training
over a ``jax.sharding.Mesh``: fleet rollups partitioned over hosts with
XLA collectives doing the reduction, and the telemetry-forecast train
step sharded data-parallel × model-parallel. Two explicit ICI
patterns complement the psum-based rollup: a ppermute ring all-reduce
(the neighbor-only schedule psum lowers to on torus links) and
sequence-parallel windowing with halo exchange over a ``seq`` mesh —
each shard fetches only its boundary samples from its ring neighbor,
the long-context recipe. Multi-chip is exercised on a virtual CPU mesh
in tests and by the driver's dryrun.
"""

from .mesh import (
    alltoall_generation_histogram,
    fleet_mesh,
    ring_allreduce,
    ring_rollup,
    seq_mesh,
    sharded_make_windows,
    sharded_rollup,
    train_mesh,
)

__all__ = [
    "alltoall_generation_histogram",
    "fleet_mesh",
    "ring_allreduce",
    "ring_rollup",
    "seq_mesh",
    "sharded_make_windows",
    "sharded_rollup",
    "train_mesh",
]
