"""Parallelism — device meshes and sharded fleet analytics.

The reference has no distributed compute (SURVEY.md §2.3); the TPU
framework's distributed surface is SPMD analytics and model training
over a ``jax.sharding.Mesh``: fleet rollups partitioned over hosts with
XLA collectives doing the reduction, and the telemetry-forecast train
step sharded data-parallel × model-parallel. Multi-chip is exercised on
a virtual CPU mesh in tests and by the driver's dryrun.
"""

from .mesh import fleet_mesh, sharded_rollup, train_mesh

__all__ = ["fleet_mesh", "sharded_rollup", "train_mesh"]
