"""Cursor-windowed row selection over the snapshot (ADR-026).

The cost model: the first window cut from a new snapshot generation
pays one O(N log N) sort per (collection, filter, region); the result
is memoized on the snapshot view, and every later window — any client,
any page depth — is a binary search plus an O(limit) slice. That is
what makes a 16k-node windowed paint cost what a 1k-node paint costs
(the ``bench_viewport`` acceptance number): N only enters through a
per-generation sort amortized across every request of that generation.

Sort orders are the ones the legacy pages already pinned: nodes
not-ready-first then by name, pods by namespaced name, trend series by
label. The sort KEY doubles as the cursor key — see ``cursor.py`` for
why seek cursors survive churn where offsets do not.
"""

from __future__ import annotations

import threading
from bisect import bisect_right
from dataclasses import dataclass
from typing import Any, Callable

from ..domain import objects as obj
from .cursor import (
    SORT_NODES,
    SORT_PODS,
    SORT_SERIES,
    decode_cursor,
    encode_cursor,
    query_hash,
)
from .tree import viewport_tree

#: Default window size — one screenful of rows.
DEFAULT_LIMIT = 64
#: Hard ceiling; a windowed response is bounded no matter the query.
MAX_LIMIT = 512

_MEMO_LOCK = threading.Lock()


@dataclass(frozen=True)
class Window:
    """One cursor window: the rows, where they sit, how to continue."""

    rows: list[Any]
    total: int
    start: int
    next_cursor: str | None
    generation: int | None
    limit: int


def clamp_limit(limit: int) -> int:
    return min(max(int(limit), 1), MAX_LIMIT)


def _view_memo(view: Any) -> dict:
    """The per-snapshot memo dict, attached to the view object itself —
    its lifetime IS the generation's lifetime, so there is no staleness
    to manage and no cross-app key collision (ADR-012's lesson)."""
    memo = getattr(view, "_viewport_memo", None)
    if memo is None:
        with _MEMO_LOCK:
            memo = getattr(view, "_viewport_memo", None)
            if memo is None:
                memo = {}
                view._viewport_memo = memo
    return memo


def _memoized(view: Any, key: tuple, build: Callable[[], Any]) -> Any:
    """Versioned views memoize ``build()`` under ``key``; unversioned
    views (CLI one-shots, raw test views) compute every call — exactly
    the device cache's contract."""
    if getattr(view, "version", None) is None:
        return build()
    memo = _view_memo(view)
    value = memo.get(key)
    if value is None:
        value = build()
        with _MEMO_LOCK:
            value = memo.setdefault(key, value)
    return value


def pods_by_node(state: Any) -> dict[str, list[Any]]:
    """nodeName -> pods, built once per snapshot generation. The
    viewport twin of the old per-request ``pages.common.pods_by_node``
    pass — pages get the map through here so VPT001 can hold."""

    def build() -> dict[str, list[Any]]:
        out: dict[str, list[Any]] = {}
        for p in state.pods:
            node = obj.pod_node_name(p)
            if node:
                out.setdefault(node, []).append(p)
        return out

    return _memoized(state.view, ("pods_by_node",), build)


def running_chips(state: Any) -> int:
    """Chips requested by Running pods — the workload-summary number,
    computed once per generation (legacy pages re-summed the full pod
    list per request). Counts every Running pod, scheduled or not, so
    the summary matches the pre-viewport bytes exactly."""
    from ..domain import tpu

    def build() -> int:
        return sum(
            tpu.get_pod_chip_request(p)
            for p in state.pods
            if obj.pod_phase(p) == "Running"
        )

    return _memoized(state.view, ("running_chips",), build)


def pending_pods(state: Any) -> list[Any]:
    """Pending pods in snapshot order, once per generation — the
    attention-table input."""

    def build() -> list[Any]:
        return [p for p in state.pods if obj.pod_phase(p) == "Pending"]

    return _memoized(state.view, ("pending_pods",), build)


def _node_key(node: Any) -> tuple[int, str]:
    return (1 if obj.is_node_ready(node) else 0, obj.name(node))


def _pod_key(pod: Any) -> tuple[str]:
    ns = obj.namespace(pod)
    name = obj.name(pod)
    return (f"{ns}/{name}" if ns else name,)


def _sorted_nodes(
    state: Any, query: str, region: str | None
) -> tuple[list[tuple], list[Any]]:
    """(sorted keys, same-order nodes) for one (filter, region) — THE
    per-generation O(N log N) pass."""

    def build() -> tuple[list[tuple], list[Any]]:
        nodes = state.nodes
        if region is not None:
            member = set(viewport_tree(state).members.get(region, ()))
            nodes = [n for n in nodes if obj.name(n) in member]
        if query:
            needle = query.lower()
            nodes = [n for n in nodes if needle in obj.name(n).lower()]
        keyed = sorted(((_node_key(n), n) for n in nodes), key=lambda kv: kv[0])
        return [k for k, _n in keyed], [n for _k, n in keyed]

    return _memoized(
        state.view, ("nodes", query_hash(query), region or ""), build
    )


def _sorted_pods(
    state: Any, query: str, region: str | None
) -> tuple[list[tuple], list[Any]]:
    def build() -> tuple[list[tuple], list[Any]]:
        pods = state.pods
        if region is not None:
            member = set(viewport_tree(state).members.get(region, ()))
            pods = [
                p for p in pods if (obj.pod_node_name(p) or "") in member
            ]
        if query:
            needle = query.lower()
            pods = [p for p in pods if needle in _pod_key(p)[0].lower()]
        keyed = sorted(((_pod_key(p), p) for p in pods), key=lambda kv: kv[0])
        return [k for k, _p in keyed], [p for _k, p in keyed]

    return _memoized(
        state.view, ("pods", query_hash(query), region or ""), build
    )


def _cut(
    keys: list[tuple],
    items: list[Any],
    *,
    sort: str,
    query: str,
    limit: int,
    cursor: str | None,
    generation: int | None,
) -> Window:
    """Seek + slice: binary-search past the cursor key, take ``limit``
    rows, mint the continuation cursor from the last one."""
    limit = clamp_limit(limit)
    start = 0
    decoded = decode_cursor(cursor) if cursor else None
    if (
        decoded is not None
        and decoded.sort == sort
        and decoded.query_hash == query_hash(query)
    ):
        start = bisect_right(keys, decoded.last_key)
    rows = items[start : start + limit]
    next_cursor = None
    if start + limit < len(items) and rows:
        next_cursor = encode_cursor(
            generation=generation or 0,
            sort=sort,
            query=query,
            last_key=keys[start + len(rows) - 1],
        )
    return Window(
        rows=rows,
        total=len(items),
        start=start,
        next_cursor=next_cursor,
        generation=generation,
        limit=limit,
    )


def window_nodes(
    state: Any,
    *,
    limit: int = DEFAULT_LIMIT,
    cursor: str | None = None,
    query: str = "",
    region: str | None = None,
) -> Window:
    """A cursor window of nodes, not-ready-first then by name —
    optionally restricted to one drill-down region."""
    keys, items = _sorted_nodes(state, query, region)
    return _cut(
        keys,
        items,
        sort=SORT_NODES,
        query=query,
        limit=limit,
        cursor=cursor,
        generation=getattr(state.view, "version", None),
    )


def window_pods(
    state: Any,
    *,
    limit: int = DEFAULT_LIMIT,
    cursor: str | None = None,
    query: str = "",
    region: str | None = None,
) -> Window:
    """A cursor window of pods in namespaced-name order."""
    keys, items = _sorted_pods(state, query, region)
    return _cut(
        keys,
        items,
        sort=SORT_PODS,
        query=query,
        limit=limit,
        cursor=cursor,
        generation=getattr(state.view, "version", None),
    )


def window_series(
    labels_and_items: list[tuple[str, Any]],
    *,
    limit: int = DEFAULT_LIMIT,
    cursor: str | None = None,
    query: str = "",
    generation: int | None = None,
) -> Window:
    """A cursor window over trend series, sorted by label — label order
    is stable under value churn, which is exactly why the busiest-first
    grouped view cannot page but this listing can. The caller passes
    (label, item) pairs; no snapshot memo here because the history tier
    already hands over a point-in-time list."""
    keyed = sorted(labels_and_items, key=lambda kv: kv[0])
    keys: list[tuple] = [(label,) for label, _item in keyed]
    items = [item for _label, item in keyed]
    return _cut(
        keys,
        items,
        sort=SORT_SERIES,
        query=query,
        limit=limit,
        cursor=cursor,
        generation=generation,
    )
