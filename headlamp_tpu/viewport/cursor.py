"""Seek cursors for windowed tables (ADR-026).

A cursor is NOT an offset. Offsets break under churn: delete one node
while a viewer is on page 3 and every later page shifts — rows skip or
repeat. A seek cursor instead records the SORT KEY of the last row the
client saw; the next window is "rows strictly after this key", which is
stable against insertions and deletions anywhere else in the fleet (a
surviving row is never skipped or repeated; for a pinned generation the
pages tile the fleet exactly).

The token is urlsafe base64 over compact JSON — opaque to clients,
inspectable in a debugger — carrying:

``g``
    snapshot generation the window was cut from (observability + the
    ETag/coalesce key; seek semantics do not need it to be current).
``s``
    sort id (``rn`` ready-then-name node order, ``nn`` namespaced pod
    name, ``lb`` trend series label). A cursor replayed against a
    different sort is ignored, never misapplied.
``q``
    8-hex hash of the filter query the window was cut under — same
    guard, a cursor never carries across filters.
``k``
    the last row's sort key (JSON array of ints/strings).

Malformed, truncated, or tampered tokens decode to ``None`` and the
window starts from the top — a cursor can degrade a request to page 1,
never break it.
"""

from __future__ import annotations

import base64
import binascii
import hashlib
import json
from dataclasses import dataclass

#: Sort ids — the ``s`` vocabulary.
SORT_NODES = "rn"
SORT_PODS = "nn"
SORT_SERIES = "lb"

_MAX_TOKEN = 512  # hard cap: a cursor is ~tens of bytes, never KBs


def query_hash(query: str) -> str:
    """Stable 8-hex digest binding a cursor to its filter."""
    return hashlib.sha1(query.encode("utf-8")).hexdigest()[:8]


@dataclass(frozen=True)
class Cursor:
    generation: int
    sort: str
    query_hash: str
    last_key: tuple


def encode_cursor(
    *, generation: int, sort: str, query: str, last_key: tuple
) -> str:
    payload = json.dumps(
        {
            "g": int(generation),
            "s": sort,
            "q": query_hash(query),
            "k": list(last_key),
        },
        separators=(",", ":"),
        sort_keys=True,
    )
    return (
        base64.urlsafe_b64encode(payload.encode("utf-8"))
        .decode("ascii")
        .rstrip("=")
    )


def decode_cursor(token: str) -> Cursor | None:
    if not token or len(token) > _MAX_TOKEN:
        return None
    try:
        padded = token + "=" * (-len(token) % 4)
        payload = json.loads(base64.urlsafe_b64decode(padded.encode("ascii")))
    except (binascii.Error, ValueError, UnicodeDecodeError):
        return None
    if not isinstance(payload, dict):
        return None
    generation = payload.get("g")
    sort = payload.get("s")
    qh = payload.get("q")
    key = payload.get("k")
    if (
        not isinstance(generation, int)
        or not isinstance(sort, str)
        or not isinstance(qh, str)
        or not isinstance(key, list)
        or not all(isinstance(part, (int, str)) for part in key)
    ):
        return None
    return Cursor(
        generation=generation, sort=sort, query_hash=qh, last_key=tuple(key)
    )
