"""Viewport layer (ADR-026): O(what-the-viewer-sees) serving.

Between the snapshot and the pages sits this package. Pages stopped
iterating the fleet (machine-enforced by VPT001): they ask the viewport
for a drill-down tree (``tree.viewport_tree`` — per-region rollups
computed device-side at scale), a cursor-stable row window
(``window.window_nodes`` / ``window_pods`` — seek cursors that survive
fleet churn), or a memoized derived map (``window.pods_by_node``).
Per-request cost is O(limit + regions), never O(fleet); the O(N) passes
run once per snapshot generation and are memoized on the snapshot view
itself, so leader and ADR-025 replicas each derive identical bytes from
identical snapshots.
"""

from .cursor import decode_cursor, encode_cursor, query_hash
from .tree import (
    Region,
    ViewportTree,
    node_region,
    parse_region,
    region_path,
    viewport_tree,
)
from .window import (
    Window,
    pending_pods,
    pods_by_node,
    running_chips,
    window_nodes,
    window_pods,
    window_series,
)

__all__ = [
    "Region",
    "ViewportTree",
    "Window",
    "decode_cursor",
    "encode_cursor",
    "node_region",
    "parse_region",
    "pending_pods",
    "pods_by_node",
    "query_hash",
    "region_path",
    "running_chips",
    "viewport_tree",
    "window_nodes",
    "window_pods",
    "window_series",
]
