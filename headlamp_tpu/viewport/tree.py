"""Hierarchical drill-down tree: fleet → cluster → slice → node (ADR-026).

Region identity is name-based and total: every node belongs to exactly
one cluster (its :data:`~headlamp_tpu.domain.constants.HEADLAMP_CLUSTER_LABEL`
value, ``"0"`` when unlabelled — i.e. every single-cluster deployment)
and one slice (its GKE node pool, ``"-"`` for single-host/plain nodes).
A drill-down path is ``cluster/<ck>`` or ``cluster/<ck>/slice/<sk>``;
the same strings key region-scoped push subscriptions
(``/events?region=...``) and the region page models the differ emits.

Per-region rollups follow the ADR-012/020 aggregate-before-transfer
discipline: at ``XLA_ROLLUP_MIN_NODES`` and above the sums come from
the fused ``analytics.region_rollup`` program over the device-cached
columns (both drill-down levels in ONE dispatch — what crosses the
device boundary is a few region-sized vectors, never 16k node rows);
below the floor, or when the device path fails, a single Python pass
computes the identical numbers (pinned by test). Either way the result
is memoized ON the snapshot view object, so the whole tree costs O(N)
once per snapshot generation and O(regions) per request after that —
and two processes holding byte-identical snapshots (leader and ADR-025
replica) derive byte-identical trees.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Mapping

from ..domain import objects as obj
from ..domain import tpu
from ..domain.constants import HEADLAMP_CLUSTER_LABEL

#: Cluster key for nodes without the federation label.
DEFAULT_CLUSTER = "0"
#: Slice key for nodes outside any GKE node pool.
NO_SLICE = "-"

_MEMO_LOCK = threading.Lock()

#: Rollup stat keys, in render order — one vocabulary for the device
#: vectors, the host fallback, and the region cells the differ pushes.
STAT_KEYS = ("nodes", "ready", "capacity", "allocatable", "in_use", "pending")


def node_region(node: Any) -> tuple[str, str]:
    """(cluster key, slice key) for ``node`` — total over any fleet."""
    cluster = obj.labels(node).get(HEADLAMP_CLUSTER_LABEL) or DEFAULT_CLUSTER
    return cluster, tpu.get_node_pool(node) or NO_SLICE


def region_path(cluster: str, slice_: str | None = None) -> str:
    """Canonical drill-down path for a region."""
    if slice_ is None:
        return f"cluster/{cluster}"
    return f"cluster/{cluster}/slice/{slice_}"


def parse_region(path: str) -> tuple[str, str | None] | None:
    """Parse a drill-down path back into (cluster, slice-or-None);
    None for anything that is not a canonical region path. Keys are
    opaque label values — only the path grammar is validated."""
    parts = path.strip("/").split("/")
    if len(parts) == 2 and parts[0] == "cluster" and parts[1]:
        return parts[1], None
    if (
        len(parts) == 4
        and parts[0] == "cluster"
        and parts[2] == "slice"
        and parts[1]
        and parts[3]
    ):
        return parts[1], parts[3]
    return None


@dataclass(frozen=True)
class Region:
    """One drill-down region: its canonical path, display key, rollup
    stats (:data:`STAT_KEYS`), and child regions (clusters carry their
    slices; slices carry no children — node rows come from the window
    layer, not the tree)."""

    path: str
    key: str
    level: str  # "cluster" | "slice"
    stats: dict[str, int]
    children: tuple["Region", ...] = ()


@dataclass(frozen=True)
class ViewportTree:
    """The whole drill-down hierarchy for one snapshot generation."""

    generation: int | None
    total: dict[str, int]
    clusters: tuple[Region, ...]
    #: node name -> (cluster key, slice key)
    region_of: Mapping[str, tuple[str, str]]
    #: region path -> member node names (both levels)
    members: Mapping[str, tuple[str, ...]]
    source: str  # "device" | "host"

    def region(self, path: str) -> Region | None:
        for cluster in self.clusters:
            if cluster.path == path:
                return cluster
            for slc in cluster.children:
                if slc.path == path:
                    return slc
        return None


def _assignments(
    nodes: list[Any],
) -> tuple[
    dict[str, tuple[str, str]],
    list[str],
    list[tuple[str, str]],
    dict[str, int],
    dict[tuple[str, str], int],
]:
    """One pass over the node list: per-node region, sorted cluster and
    slice vocabularies, and key→ordinal maps (the segment ids the
    device program sums into)."""
    region_of: dict[str, tuple[str, str]] = {}
    for node in nodes:
        region_of[obj.name(node)] = node_region(node)
    clusters = sorted({ck for ck, _sk in region_of.values()})
    slices = sorted(set(region_of.values()))
    cluster_id = {ck: i for i, ck in enumerate(clusters)}
    slice_id = {pair: i for i, pair in enumerate(slices)}
    return region_of, clusters, slices, cluster_id, slice_id


def _device_sums(
    view: Any,
    cluster_id: dict[str, int],
    slice_id: dict[tuple[str, str], int],
    region_of: dict[str, tuple[str, str]],
    segments_limit: int,
) -> tuple[list[dict[str, int]], list[dict[str, int]]]:
    """Per-cluster and per-slice stat dicts from ONE fused device
    dispatch over the ADR-012 cached columns."""
    import numpy as np

    from ..analytics.fleet_jax import region_rollup_arrays
    from ..runtime import transfer
    from ..runtime.device_cache import fleet_cache

    fleet = fleet_cache.fleet_for(view)
    pad = int(fleet.node_capacity.shape[0])
    node_cluster = np.zeros(pad, dtype=np.int32)
    node_slice = np.zeros(pad, dtype=np.int32)
    for i, name in enumerate(fleet.node_names):
        ck, sk = region_of[name]
        node_cluster[i] = min(cluster_id[ck], segments_limit - 1)
        node_slice[i] = slice_id[(ck, sk)]
    out = transfer.fetch(region_rollup_arrays(fleet, node_cluster, node_slice))

    def stats_at(prefix: str, idx: int) -> dict[str, int]:
        return {
            "nodes": int(out[f"{prefix}_nodes"][idx]),
            "ready": int(out[f"{prefix}_ready"][idx]),
            "capacity": int(out[f"{prefix}_capacity"][idx]),
            "allocatable": int(out[f"{prefix}_allocatable"][idx]),
            "in_use": int(out[f"{prefix}_in_use"][idx]),
            "pending": int(out[f"{prefix}_pending"][idx]),
        }

    cluster_stats = [
        stats_at("cluster", min(cid, segments_limit - 1))
        for cid in range(len(cluster_id))
    ]
    slice_stats = [stats_at("slice", sid) for sid in range(len(slice_id))]
    return cluster_stats, slice_stats


def _host_sums(
    state: Any,
    cluster_id: dict[str, int],
    slice_id: dict[tuple[str, str], int],
    region_of: dict[str, tuple[str, str]],
    segments_limit: int,
) -> tuple[list[dict[str, int]], list[dict[str, int]]]:
    """Python twin of :func:`_device_sums` — the below-floor/fallback
    path, and the oracle the device numbers are pinned against. The
    viewport IS the aggregation layer, so this is one of the two places
    a full-fleet loop is legitimate (the other is the encoder)."""
    zeros = lambda: {k: 0 for k in STAT_KEYS}  # noqa: E731
    cluster_stats = [zeros() for _ in cluster_id]
    slice_stats = [zeros() for _ in slice_id]

    def effective_cid(ck: str) -> int:
        return min(cluster_id[ck], segments_limit - 1)

    merged: dict[int, dict[str, int]] = {}
    for node in state.nodes:
        ck, sk = region_of[obj.name(node)]
        cid, sid = effective_cid(ck), slice_id[(ck, sk)]
        cstats = merged.setdefault(cid, zeros())
        for stats in (cstats, slice_stats[sid]):
            stats["nodes"] += 1
            stats["ready"] += 1 if obj.is_node_ready(node) else 0
            stats["capacity"] += tpu.get_node_chip_capacity(node)
            stats["allocatable"] += tpu.get_node_chip_allocatable(node)
    for pod in state.pods:
        node_name = obj.pod_node_name(pod)
        if not node_name or node_name not in region_of:
            continue
        ck, sk = region_of[node_name]
        cid, sid = effective_cid(ck), slice_id[(ck, sk)]
        cstats = merged.setdefault(cid, zeros())
        phase = obj.pod_phase(pod)
        if phase == "Running":
            request = tpu.get_pod_chip_request(pod)
            cstats["in_use"] += request
            slice_stats[sid]["in_use"] += request
        elif phase == "Pending":
            cstats["pending"] += 1
            slice_stats[sid]["pending"] += 1
    # Clusters clamped into one segment all read the merged sums — the
    # same aliasing the device's clip produces past the segment limit.
    for ck, cid in cluster_id.items():
        cluster_stats[cid] = dict(merged.get(effective_cid(ck), zeros()))
    return cluster_stats, slice_stats


def _build_tree(state: Any) -> ViewportTree:
    from ..analytics.fleet_jax import REGION_CLUSTER_SEGMENTS
    from ..analytics.stats import XLA_ROLLUP_MIN_NODES

    view = state.view
    nodes = state.nodes
    region_of, clusters, slices, cluster_id, slice_id = _assignments(nodes)

    source = "host"
    if len(nodes) >= XLA_ROLLUP_MIN_NODES:
        try:
            cluster_stats, slice_stats = _device_sums(
                view, cluster_id, slice_id, region_of, REGION_CLUSTER_SEGMENTS
            )
            source = "device"
        except Exception:  # noqa: BLE001 — same fallback contract as fleet_stats
            cluster_stats, slice_stats = _host_sums(
                state, cluster_id, slice_id, region_of, REGION_CLUSTER_SEGMENTS
            )
    else:
        cluster_stats, slice_stats = _host_sums(
            state, cluster_id, slice_id, region_of, REGION_CLUSTER_SEGMENTS
        )

    members: dict[str, list[str]] = {}
    for name, (ck, sk) in region_of.items():
        members.setdefault(region_path(ck), []).append(name)
        members.setdefault(region_path(ck, sk), []).append(name)
    frozen_members = {
        path: tuple(sorted(names)) for path, names in members.items()
    }

    cluster_regions: list[Region] = []
    for ck in clusters:
        child_regions = tuple(
            Region(
                path=region_path(ck, sk),
                key=sk,
                level="slice",
                stats=slice_stats[slice_id[(ck, sk)]],
            )
            for ck2, sk in slices
            if ck2 == ck
        )
        cluster_regions.append(
            Region(
                path=region_path(ck),
                key=ck,
                level="cluster",
                stats=cluster_stats[cluster_id[ck]],
                children=child_regions,
            )
        )

    total = {key: 0 for key in STAT_KEYS}
    for region in cluster_regions:
        # Slice stats are exact per slice; cluster totals sum the
        # SLICE rows so segment-limit aliasing never double-counts.
        for child in region.children:
            for key in STAT_KEYS:
                total[key] += child.stats[key]

    return ViewportTree(
        generation=getattr(view, "version", None),
        total=total,
        clusters=tuple(cluster_regions),
        region_of=region_of,
        members=frozen_members,
        source=source,
    )


def viewport_tree(state: Any) -> ViewportTree:
    """The drill-down tree for ``state`` (a ``ProviderState``) —
    memoized on the snapshot view, so every page/push/bench consumer of
    one generation shares one O(N) build."""
    view = state.view
    cached = getattr(view, "_viewport_tree", None)
    if cached is not None:
        return cached
    tree = _build_tree(state)
    if getattr(view, "version", None) is not None:
        with _MEMO_LOCK:
            cached = getattr(view, "_viewport_tree", None)
            if cached is None:
                view._viewport_tree = tree
            else:
                tree = cached
    return tree
