"""Text-mode CLI — the dashboard for terminals and headless hosts.

``python -m headlamp_tpu.cli <page>`` renders the same element trees
the HTTP host serves, through ``ui.vdom.render_text``. One framework,
three consumers (HTTP, CLI, tests) — the payoff of pages being pure
functions of snapshots (ADR-001/007).

Pages: overview | nodes | pods | deviceplugins | topology | metrics |
intel | intel-nodes | intel-pods | intel-deviceplugins | intel-metrics |
cluster-nodes
"""

from __future__ import annotations

import argparse
import sys
import time

from typing import Callable

from .context.accelerator_context import AcceleratorDataContext
from .registration import register_plugin
from .transport.api_proxy import KubeTransport, Transport
from .ui import render_text

#: CLI page name -> route path.
PAGES = {
    "overview": "/tpu",
    "nodes": "/tpu/nodes",
    "pods": "/tpu/pods",
    "deviceplugins": "/tpu/deviceplugins",
    "topology": "/tpu/topology",
    "metrics": "/tpu/metrics",
    "intel": "/intel",
    "intel-nodes": "/intel/nodes",
    "intel-pods": "/intel/pods",
    "intel-deviceplugins": "/intel/deviceplugins",
    "intel-metrics": "/intel/metrics",
    "cluster-nodes": "/nodes",
}


def render_page(
    page: str, transport: Transport, *, clock: Callable[[], float] = time.time
) -> str:
    """Render one page to text against a transport (exposed for tests).

    ``clock`` is wall time on purpose (ADR-013 clock audit): every use
    below is a displayed timestamp or a Prometheus query-range bound —
    values that must agree with the cluster's real time. Nothing here
    computes an elapsed duration from it.
    """
    registry = register_plugin()
    route = registry.route_for(PAGES[page])
    assert route is not None
    if route.kind == "metrics":
        from .metrics.client import fetch_tpu_metrics

        metrics = fetch_tpu_metrics(transport, clock=clock)
        try:
            from .models.service import compute_forecast

            forecast = compute_forecast(transport, metrics, clock=clock)
        except ImportError:
            forecast = None
        return render_text(route.component(metrics, forecast))
    if route.kind == "intel-metrics":
        from .metrics.intel_client import fetch_intel_gpu_metrics

        return render_text(
            route.component(fetch_intel_gpu_metrics(transport, clock=clock))
        )
    ctx = AcceleratorDataContext(transport, clock=clock)
    snap = ctx.sync()
    if route.kind == "topology":
        return render_text(route.component(snap))
    if route.kind == "native-nodes":
        return render_text(route.component(snap, now=clock(), registry=registry))
    return render_text(route.component(snap, now=clock()))


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="headlamp_tpu.cli")
    parser.add_argument("page", choices=sorted(PAGES), nargs="?", default="overview")
    parser.add_argument("--demo", nargs="?", const="v5p32",
                        choices=["v5e4", "v5p32", "mixed", "large"], default=None)
    parser.add_argument("--apiserver", default=None)
    parser.add_argument("--in-cluster", action="store_true")
    args = parser.parse_args(argv)

    if args.demo:
        from .server.app import make_demo_transport

        transport = make_demo_transport(args.demo)
    elif args.in_cluster:
        transport = KubeTransport.in_cluster()
    elif args.apiserver:
        transport = KubeTransport(args.apiserver)
    else:
        parser.error("choose one of --demo, --apiserver URL, --in-cluster")

    print(render_page(args.page, transport))
    return 0


if __name__ == "__main__":
    sys.exit(main())
