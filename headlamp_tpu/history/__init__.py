"""In-process history tier + record-and-replay harness (ADR-018).

Every other observability surface answers "what is happening now";
this package makes the dashboard answer "how did it move":

- :mod:`.store` — :class:`HistoryStore`, a bounded columnar store of
  per-metric ring-buffer shards fed off the request path (the ADR-015
  refresher's store hook and the cluster-sync loop), read by the
  ``/tpu/trends`` page, the forecaster, and ``/healthz``.
- :mod:`.record` — :class:`RecordingTransport` serializes live
  transport traffic to a versioned JSONL artifact;
  :class:`ReplaySource` replays it deterministically behind the
  transport seam (``bench.py --replay``).

Clock discipline (ADR-013): the whole package is inside the
``no_wall_clock_check`` scope — retention, window, and replay-pacing
math run on injected monotonic clocks only.
"""

from .record import (
    RECORDING_VERSION,
    Recorder,
    Recording,
    RecordingTransport,
    ReplaySource,
    load_recording,
)
from .store import HistoryStore, active_store, set_active_store

__all__ = [
    "HistoryStore",
    "active_store",
    "set_active_store",
    "Recorder",
    "Recording",
    "RecordingTransport",
    "ReplaySource",
    "RECORDING_VERSION",
    "load_recording",
]
