"""Bounded columnar history store — the capture half of ADR-018.

One :class:`HistoryStore` holds a map of per-series ring-buffer shards.
Each shard is two preallocated columns — ``float32`` values and
``float64`` monotonic stamps (``array.array``, so the core works on a
jax-less host) — and appending is an index write plus a ring-head bump.
Everything is bounded up front: a shard never grows past its capacity
(overwrites count as evictions) and the shard map never grows past
``max_shards`` (least-recently-appended shard dropped, counted), so a
soak can run for weeks without the history tier becoming the leak.

Who writes: the ADR-015 refresher's ``on_store`` hook (every successful
scrape, on the BACKGROUND refit path — capture never extends the
request critical path) and the cluster-sync loop (one row per snapshot
generation). Who reads: the ``/tpu/trends`` page, the forecaster
(:meth:`HistoryStore.utilization_history` — real history instead of a
synthetic window once one training window has accumulated), ``/healthz``
(:meth:`snapshot`), ``/metricsz`` (module gauges below), and the flight
recorder (:meth:`counters` — monotone ints, no locks, the r10-review
rule).

Clock discipline (ADR-013): stamps are INJECTED monotonic readings;
retention and window math never touch the wall clock. Wall time enters
only where a caller hands one in (``utilization_history(clock=...)``
maps stamps onto epoch seconds for the Prometheus-shaped output).
"""

from __future__ import annotations

import array
import threading
import time
import weakref
from typing import Any, Callable, Iterable

from ..obs.metrics import registry as _metrics_registry

#: Points each shard retains. 288 points at a 60 s scrape cadence is
#: 4.8 h — roughly the default retention window; a faster cadence
#: trades span for resolution inside the same fixed memory.
SHARD_CAPACITY = 288
#: Oldest age served by windowed reads (6 h — the trend question the
#: ISSUE names). Points older than this still sit in the ring until
#: overwritten; reads filter them out.
RETENTION_S = 6 * 3600.0
#: Shard-map bound: 1024 nodes x 4 chips x 2 per-chip metrics plus the
#: fleet/sync/slo aggregate series fits with headroom. Past it, the
#: least-recently-appended shard is evicted (counted, never silent).
MAX_SHARDS = 8704

# Registry instruments (ADR-013 get-or-create). Counters dual-account
# with the per-store ints (same transition writes both) — the registry
# is the fleet view, the instance ints are the /healthz + test view.
_POINTS_TOTAL = _metrics_registry.counter(
    "headlamp_tpu_history_points_total",
    "Samples appended to the in-process history tier.",
)
_EVICTED_TOTAL = _metrics_registry.counter(
    "headlamp_tpu_history_evicted_total",
    "History samples dropped by the memory bound (ring overwrites plus "
    "points lost with evicted shards).",
)


class _Shard:
    """One series: fixed-capacity float32 value / float64 monotonic-stamp
    ring columns. Mutated only under the owning store's lock."""

    __slots__ = ("capacity", "values", "stamps", "size", "head", "last_mono")

    def __init__(self, capacity: int) -> None:
        self.capacity = capacity
        self.values = array.array("f", bytes(4 * capacity))
        self.stamps = array.array("d", bytes(8 * capacity))
        self.size = 0
        self.head = 0  # next write slot
        self.last_mono = float("-inf")

    def append(self, mono: float, value: float) -> int:
        """Write one point; returns how many points were overwritten."""
        evicted = 1 if self.size == self.capacity else 0
        self.values[self.head] = value
        self.stamps[self.head] = mono
        self.head = (self.head + 1) % self.capacity
        if self.size < self.capacity:
            self.size += 1
        self.last_mono = mono
        return evicted

    def ordered(self) -> tuple[array.array, array.array]:
        """(stamps, values) oldest→newest, as fresh arrays (two C-level
        slice copies — no per-point Python loop)."""
        if self.size < self.capacity:
            return self.stamps[: self.size], self.values[: self.size]
        return (
            self.stamps[self.head:] + self.stamps[: self.head],
            self.values[self.head:] + self.values[: self.head],
        )

    def oldest_mono(self) -> float:
        if self.size == 0:
            return float("inf")
        if self.size < self.capacity:
            return self.stamps[0]
        return self.stamps[self.head]

    def memory_bytes(self) -> int:
        return 4 * self.capacity + 8 * self.capacity


def _jnp() -> Any | None:
    """jax.numpy when importable — the store core stays stdlib-only."""
    try:
        import jax.numpy as jnp

        return jnp
    except Exception:  # noqa: BLE001 — jax-less host: lists still serve
        return None


class HistoryStore:
    """Bounded in-process history tier. Lock-light by construction: one
    plain lock guards the shard map, taken once per *batch* (a scrape
    appends every chip row under a single acquisition), and the
    flight-recorder counter view reads ints without it."""

    def __init__(
        self,
        *,
        shard_capacity: int = SHARD_CAPACITY,
        retention_s: float = RETENTION_S,
        max_shards: int = MAX_SHARDS,
        monotonic: Callable[[], float] | None = None,
    ) -> None:
        if shard_capacity < 2:
            raise ValueError("shard_capacity must be >= 2")
        self.shard_capacity = shard_capacity
        self.retention_s = retention_s
        self.max_shards = max_shards
        self._monotonic = monotonic or time.monotonic
        #: Whether locally MEASURED durations (snapshot.fetch_ms) are
        #: captured. Replay harnesses set this False: the determinism
        #: contract covers replayed data, and a perf_counter reading
        #: taken on the replaying host is environment noise that would
        #: break byte-parity between two runs of the same artifact.
        self.capture_timings = True
        self._lock = threading.Lock()
        self._shards: dict[tuple[str, tuple[str, ...]], _Shard] = {}
        # Monotone ints (flight-recorder counters view; registry
        # counters mirror the same transitions).
        self.points = 0
        self.points_evicted = 0
        self.shards_evicted = 0
        self.scrapes = 0
        self.syncs = 0

    # -- write path ------------------------------------------------------

    def append(
        self, metric: str, value: float, *, labels: Iterable[str] = ()
    ) -> None:
        self.append_many(((metric, tuple(labels), value),))

    def append_many(
        self, rows: Iterable[tuple[str, tuple[str, ...], float]]
    ) -> int:
        """Append a batch of ``(metric, labels, value)`` rows stamped at
        one monotonic instant (a scrape is one instant — per-chip rows
        must land on the same grid point). Returns rows appended."""
        now = self._monotonic()
        appended = 0
        overwritten = 0
        dropped = 0
        with self._lock:
            for metric, labels, value in rows:
                key = (metric, labels)
                shard = self._shards.get(key)
                created = shard is None
                if created:
                    shard = self._shards[key] = _Shard(self.shard_capacity)
                overwritten += shard.append(now, float(value))
                appended += 1
                if created:
                    # Enforce AFTER the first append: the new shard now
                    # carries a current stamp, so the LRU pick can never
                    # evict the series being written.
                    dropped += self._enforce_shard_bound_locked()
            self.points += appended
            self.points_evicted += overwritten + dropped
        if appended:
            _POINTS_TOTAL.inc(appended)
        if overwritten + dropped:
            _EVICTED_TOTAL.inc(overwritten + dropped)
        return appended

    def _enforce_shard_bound_locked(self) -> int:
        """Drop least-recently-appended shards past ``max_shards``;
        returns live points lost. Caller holds the lock."""
        dropped = 0
        while len(self._shards) > self.max_shards:
            victim = min(
                self._shards, key=lambda k: self._shards[k].last_mono
            )
            dropped += self._shards[victim].size
            del self._shards[victim]
            self.shards_evicted += 1
        return dropped

    # -- capture adapters ------------------------------------------------

    def record_scrape(self, snapshot: Any) -> int:
        """Capture one successful TPU metrics scrape
        (``TpuMetricsSnapshot``): per-chip utilization/duty-cycle shards
        plus fleet aggregates, all on one grid stamp. Returns rows
        appended; any malformed snapshot is worth 0 rows, never an
        exception (capture must not break serving)."""
        try:
            chips = snapshot.chips
        except AttributeError:
            return 0
        rows: list[tuple[str, tuple[str, ...], float]] = []
        util_sum, util_n = 0.0, 0
        for chip in chips:
            chip_key = (str(chip.node), str(chip.accelerator_id))
            util = chip.tensorcore_utilization
            if util is not None:
                rows.append(("chip.tensorcore_utilization", chip_key, util))
                util_sum += util
                util_n += 1
            duty = chip.duty_cycle
            if duty is not None:
                rows.append(("chip.duty_cycle", chip_key, duty))
        rows.append(("fleet.chips_reporting", (), float(len(chips))))
        if util_n:
            rows.append(
                ("fleet.mean_tensorcore_utilization", (), util_sum / util_n)
            )
        fetch_ms = getattr(snapshot, "fetch_ms", None)
        if fetch_ms is not None and self.capture_timings:
            rows.append(("fleet.scrape_ms", (), float(fetch_ms)))
        appended = self.append_many(rows)
        self.scrapes += 1
        return appended

    def record_timing(
        self, metric: str, value: float, *, labels: Iterable[str] = ()
    ) -> bool:
        """Capture one locally MEASURED duration/overhead series (the
        ADR-019 profiler and JAX cost ledger write through here). Gated
        by ``capture_timings`` like ``fleet.scrape_ms``: a perf_counter
        reading taken on the replaying host is environment noise that
        would break two-round byte-parity, so replay harnesses drop
        these rows wholesale. Returns whether the row was captured."""
        if not self.capture_timings:
            return False
        self.append(metric, float(value), labels=labels)
        return True

    def record_sync(
        self, *, generation: int, nodes: int, errors: int = 0
    ) -> None:
        """Capture one cluster-sync snapshot generation."""
        self.append_many(
            (
                ("sync.generation", (), float(generation)),
                ("sync.nodes", (), float(nodes)),
                ("sync.errors", (), float(errors)),
            )
        )
        self.syncs += 1

    # -- read paths ------------------------------------------------------

    def series(
        self,
        metric: str,
        labels: Iterable[str] = (),
        *,
        window_s: float | None = None,
    ) -> tuple[list[float], list[float]]:
        """(ages_s, values) oldest→newest for one series, windowed to
        ``window_s`` (default: full retention). Ages are seconds before
        "now" on the injected monotonic — display layers render them
        relative ("3m ago"), which no NTP step can corrupt."""
        now = self._monotonic()
        cutoff = now - min(
            self.retention_s, window_s if window_s is not None else self.retention_s
        )
        with self._lock:
            shard = self._shards.get((metric, tuple(labels)))
            if shard is None:
                return [], []
            stamps, values = shard.ordered()
        ages: list[float] = []
        vals: list[float] = []
        for stamp, value in zip(stamps, values):
            if stamp >= cutoff:
                ages.append(now - stamp)
                vals.append(value)
        return ages, vals

    def window_arrays(
        self,
        metric: str,
        labels: Iterable[str] = (),
        *,
        window_s: float | None = None,
    ) -> tuple[Any, Any]:
        """(ages, values) as ``jnp`` arrays (float32 values) so
        analytics/ and models/ consume history without a Python-loop
        copy; plain lists on a jax-less host."""
        ages, vals = self.series(metric, labels, window_s=window_s)
        jnp = _jnp()
        if jnp is None:
            return ages, vals
        return (
            jnp.asarray(ages, dtype=jnp.float32),
            jnp.asarray(vals, dtype=jnp.float32),
        )

    def utilization_history(
        self,
        *,
        clock: Callable[[], float],
        min_points: int,
        max_chips: int = 256,
    ) -> Any | None:
        """The forecaster's input, built from CAPTURED per-chip
        utilization instead of a live range query: a
        ``UtilizationHistory`` when at least one chip shard holds
        ``min_points`` retained points, else None (caller falls back to
        the live window — the store must fill one training window
        before it may claim to be the data source). ``clock`` (wall) is
        used ONLY to stamp the output's display ``end``; alignment runs
        on the scrape grid itself: every chip row of one scrape shares
        one monotonic stamp, so "last N points per qualifying shard" IS
        the aligned grid."""
        from ..metrics.client import UtilizationHistory

        now = self._monotonic()
        cutoff = now - self.retention_s
        picked: list[tuple[tuple[str, str], list[float], list[float]]] = []
        with self._lock:
            for (metric, labels), shard in self._shards.items():
                if metric != "chip.tensorcore_utilization" or len(labels) != 2:
                    continue
                if shard.size < min_points:
                    continue
                stamps, values = shard.ordered()
                if stamps[-min_points] < cutoff:
                    continue  # window would reach past retention
                picked.append(
                    (
                        (labels[0], labels[1]),
                        stamps[-min_points:].tolist(),
                        values[-min_points:].tolist(),
                    )
                )
                if len(picked) >= max_chips:
                    break
        if not picked:
            return None
        picked.sort(key=lambda row: row[0])
        stamps = picked[0][1]
        deltas = [b - a for a, b in zip(stamps, stamps[1:])]
        deltas = [d for d in deltas if d > 0]
        step_s = max(1, round(sorted(deltas)[len(deltas) // 2])) if deltas else 1
        return UtilizationHistory(
            keys=[key for key, _, _ in picked],
            series=[values for _, _, values in picked],
            step_s=step_s,
            end=clock(),
            resolved_query="history:chip.tensorcore_utilization",
        )

    def trend_view(
        self,
        *,
        window_s: float,
        max_series_per_metric: int = 8,
        metric: str = "",
        series_cursor: str | None = None,
        series_limit: int | None = None,
    ) -> dict[str, Any]:
        """Page-ready view for ``/tpu/trends``: per-metric groups of
        windowed series with stats, plus the store's own health numbers.
        Plain data — the page stays a pure function of this dict.

        Two passes, so the page path is O(shards + rendered points),
        not O(total points): a cheap scan picks each metric's busiest
        ``max_series_per_metric`` series by NEWEST value (stamps only
        grow, so a shard has in-window points iff its newest stamp
        does), then only the winners materialize point lists and stats
        — at 8k full shards this is the difference between ~10 ms and
        ~10 s for one render.

        With ``metric`` set the view is the BROWSE mode instead
        (ADR-026): a label-sorted cursor window over EVERY in-window
        series of that one metric, so nothing the grouped view's
        busiest-N cap hides is unreachable — only the window's series
        materialize points, keeping the render O(limit)."""
        window_s = min(max(window_s, 1.0), self.retention_s)
        now = self._monotonic()
        cutoff = now - window_s
        candidates: dict[str, list[tuple[float, tuple[str, ...], _Shard]]] = {}
        with self._lock:
            for (m, labels), shard in self._shards.items():
                if shard.size == 0 or shard.last_mono < cutoff:
                    continue
                newest = shard.values[shard.head - 1]
                candidates.setdefault(m, []).append((newest, labels, shard))

        def materialize(
            labels: tuple[str, ...], shard: _Shard
        ) -> dict[str, Any] | None:
            with self._lock:
                stamps, values = shard.ordered()
            points = [
                (now - stamp, value)
                for stamp, value in zip(stamps, values)
                if stamp >= cutoff
            ]
            if not points:
                return None  # evicted between the passes
            return {
                "label": "/".join(labels) or "fleet",
                "points": points,
                "stats": self._stats([v for _, v in points]),
            }

        if metric:
            from ..viewport import window_series

            rows = candidates.get(metric, [])
            pairs = [
                ("/".join(labels) or "fleet", (labels, shard))
                for _newest, labels, shard in rows
            ]
            win = window_series(
                pairs,
                limit=series_limit if series_limit is not None else 64,
                cursor=series_cursor,
            )
            series = [
                s
                for labels, shard in win.rows
                if (s := materialize(labels, shard)) is not None
            ]
            return {
                "window_s": window_s,
                "retention_s": self.retention_s,
                "groups": [],
                "browse": {
                    "metric": metric,
                    "series": series,
                    "window": win,
                },
                "store": self.snapshot(),
            }
        groups = []
        for group_metric in sorted(candidates):
            rows = candidates[group_metric]
            # Busiest series first; the cap keeps a 4096-chip fleet's
            # trend page a page, not a dump.
            rows.sort(key=lambda r: (-r[0], r[1]))
            series = [
                s
                for _newest, labels, shard in rows[:max_series_per_metric]
                if (s := materialize(labels, shard)) is not None
            ]
            if series:
                groups.append(
                    {
                        "metric": group_metric,
                        "series": series,
                        "series_total": len(rows),
                    }
                )
        return {
            "window_s": window_s,
            "retention_s": self.retention_s,
            "groups": groups,
            "store": self.snapshot(),
        }

    @staticmethod
    def _stats(values: list[float]) -> dict[str, float]:
        """min/max/mean/latest/slope for one windowed series — through
        the analytics helper (jnp-fused at fleet sizes) when available,
        else the plain-Python fallback it shares."""
        try:
            from ..analytics.trends import series_stats

            return series_stats(values)
        except Exception:  # noqa: BLE001 — stats are an enhancement
            latest = values[-1] if values else 0.0
            return {
                "n": float(len(values)),
                "latest": latest,
                "min": min(values) if values else 0.0,
                "max": max(values) if values else 0.0,
                "mean": sum(values) / len(values) if values else 0.0,
                "slope_per_step": 0.0,
            }

    # -- observability ---------------------------------------------------

    def memory_bytes(self) -> int:
        with self._lock:
            return sum(s.memory_bytes() for s in self._shards.values())

    def window_span_s(self) -> float:
        """Age of the oldest retained point — how far back a trend
        question can currently be answered."""
        now = self._monotonic()
        with self._lock:
            oldest = min(
                (s.oldest_mono() for s in self._shards.values() if s.size),
                default=None,
            )
        if oldest is None:
            return 0.0
        return min(max(now - oldest, 0.0), self.retention_s)

    def counters(self) -> dict[str, int]:
        """Monotone ints only, lock-free — the flight recorder's
        per-request delta view (r10-review rule: no gauges, no locks)."""
        return {
            "points": self.points,
            "points_evicted": self.points_evicted,
            "shards_evicted": self.shards_evicted,
            "scrapes": self.scrapes,
            "syncs": self.syncs,
        }

    def snapshot(self) -> dict[str, Any]:
        """/healthz ``runtime.history`` block."""
        with self._lock:
            shards = len(self._shards)
        return {
            "points": self.points,
            "points_evicted": self.points_evicted,
            "shards": shards,
            "shards_evicted": self.shards_evicted,
            "scrapes": self.scrapes,
            "syncs": self.syncs,
            "memory_bytes": self.memory_bytes(),
            "window_span_s": round(self.window_span_s(), 3),
            "retention_s": self.retention_s,
        }


# ---------------------------------------------------------------------------
# Active-store registry gauges (the ADR-017 weakref pattern: the LATEST
# store a host wires is the one /metricsz describes; a dropped store
# must not be kept alive by its own gauges).
# ---------------------------------------------------------------------------

_ACTIVE: Any | None = None


def set_active_store(store: HistoryStore) -> None:
    global _ACTIVE
    _ACTIVE = weakref.ref(store)


def active_store() -> HistoryStore | None:
    return _ACTIVE() if _ACTIVE is not None else None


def _memory_sample() -> float | None:
    store = active_store()
    return float(store.memory_bytes()) if store is not None else None


def _span_sample() -> float | None:
    store = active_store()
    return float(store.window_span_s()) if store is not None else None


_metrics_registry.gauge_fn(
    "headlamp_tpu_history_memory_bytes",
    "Bytes held by the history tier's ring columns (bounded by "
    "shard capacity x max shards; see ADR-018's retention table).",
    _memory_sample,
)
_metrics_registry.gauge_fn(
    "headlamp_tpu_history_window_span_seconds",
    "Age of the oldest retained history point — how far back /tpu/trends "
    "can currently answer.",
    _span_sample,
)
