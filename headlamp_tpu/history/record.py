"""Record-and-replay for transport traffic — the replay half of ADR-018.

:class:`RecordingTransport` wraps any Transport and serializes every
``request()`` exchange — path, relative monotonic offset, and the parsed
JSON response or the :class:`~..transport.ApiError` it raised — to a
versioned JSONL artifact. :class:`ReplaySource` plays an artifact back
*as* a Transport, so everything above the seam (client, analytics,
pages, gateway, bench) runs unmodified against yesterday's traffic.

Determinism contract: replay answers depend only on (artifact, request
sequence, injected clock). Two replays of the same recording driven by
the same clock return byte-identical responses in byte-identical order
— which is what lets ``bench.py --replay`` turn environment-sensitive
rounds into stable ones and pins the parity test in
``tests/test_history.py``.

Two pacing modes:

- **sequential** (default, ``clock=None``): each path keeps a cursor
  advancing one recorded exchange per request, sticking at the last.
  Fully deterministic regardless of caller timing — the bench mode.
- **timed** (``clock=`` an injected monotonic): a recorded exchange
  becomes visible once ``t_rel <= elapsed * rate``; before that the
  earliest exchange serves (the fleet "as of" the replay start). With
  ``rate=3.0`` an hour of traffic plays in twenty minutes — the
  "replay yesterday at 3x" capacity scenario.

Format (one JSON object per line):

    {"v": 1, "kind": "header", "format": "headlamp-tpu-recording",
     "recorded_unix": <float>, "note": <str>}
    {"kind": "request", "t": <float rel-seconds>, "path": <str>,
     "status": "ok", "response": <json>}
    {"kind": "request", "t": ..., "path": ..., "status": "error",
     "error": {"message": <str>, "status": <int|null>}}

ADR-013: all pacing math runs on injected monotonic clocks. The one
wall reading (``recorded_unix`` in the header) is provenance metadata
through the injectable ``wall`` seam; replay never reads it.
"""

from __future__ import annotations

import io
import json
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, TextIO

from ..transport import ApiError

RECORDING_VERSION = 1
RECORDING_FORMAT = "headlamp-tpu-recording"


@dataclass(frozen=True)
class Exchange:
    """One recorded request/response pair. ``response`` is the parsed
    JSON on success; ``error`` is ``(message, status)`` on failure."""

    t_rel: float
    path: str
    response: Any = None
    error: tuple[str, int | None] | None = None


@dataclass
class Recording:
    """A parsed artifact: header metadata plus exchanges in wire order."""

    version: int
    recorded_unix: float
    note: str
    exchanges: list[Exchange] = field(default_factory=list)

    @property
    def span_s(self) -> float:
        return self.exchanges[-1].t_rel if self.exchanges else 0.0

    def paths(self) -> list[str]:
        seen: dict[str, None] = {}
        for ex in self.exchanges:
            seen.setdefault(ex.path, None)
        return list(seen)


class Recorder:
    """Serializes exchanges to JSONL. Thread-safe (the fan-out scheduler
    issues requests concurrently); offsets are relative to the first
    write on the INJECTED monotonic, so artifacts are machine-portable
    and immune to wall steps mid-recording."""

    def __init__(
        self,
        sink: TextIO,
        *,
        monotonic: Callable[[], float] | None = None,
        wall: Callable[[], float] = time.time,
        note: str = "",
    ) -> None:
        self._sink = sink
        self._monotonic = monotonic or time.monotonic
        self._lock = threading.Lock()
        self._t0: float | None = None
        self.exchanges = 0
        header = {
            "v": RECORDING_VERSION,
            "kind": "header",
            "format": RECORDING_FORMAT,
            "recorded_unix": wall(),
            "note": note,
        }
        self._sink.write(json.dumps(header, sort_keys=True) + "\n")

    def _t_rel(self) -> float:
        now = self._monotonic()
        if self._t0 is None:
            self._t0 = now
        return round(now - self._t0, 6)

    def record_ok(self, path: str, response: Any) -> None:
        with self._lock:
            line = json.dumps(
                {
                    "kind": "request",
                    "t": self._t_rel(),
                    "path": path,
                    "status": "ok",
                    "response": response,
                },
                sort_keys=True,
            )
            self._sink.write(line + "\n")
            self.exchanges += 1

    def record_error(
        self, path: str, message: str, status: int | None
    ) -> None:
        with self._lock:
            line = json.dumps(
                {
                    "kind": "request",
                    "t": self._t_rel(),
                    "path": path,
                    "status": "error",
                    "error": {"message": message, "status": status},
                },
                sort_keys=True,
            )
            self._sink.write(line + "\n")
            self.exchanges += 1


class RecordingTransport:
    """Transport decorator: pass traffic through ``inner`` verbatim,
    teeing every exchange (including failures) into ``recorder``.
    Transparent to callers — same responses, same exceptions."""

    def __init__(self, inner: Any, recorder: Recorder) -> None:
        self.inner = inner
        self.recorder = recorder

    def request(self, path: str, timeout_s: float = 2.0) -> Any:
        try:
            response = self.inner.request(path, timeout_s)
        except ApiError as err:
            # str(err) is "path: message"; strip the prefix we re-add
            # at replay so the round trip is exact.
            message = str(err)
            if message.startswith(path + ": "):
                message = message[len(path) + 2 :]
            self.recorder.record_error(path, message, err.status)
            raise
        self.recorder.record_ok(path, response)
        return response


def load_recording(path: str) -> Recording:
    """Parse a JSONL artifact, enforcing the version gate."""
    with io.open(path, "r", encoding="utf-8") as fh:
        return _parse_recording(fh, origin=path)


def _parse_recording(fh: Any, *, origin: str = "<stream>") -> Recording:
    first = fh.readline()
    if not first.strip():
        raise ValueError(f"{origin}: empty recording")
    header = json.loads(first)
    if (
        header.get("kind") != "header"
        or header.get("format") != RECORDING_FORMAT
    ):
        raise ValueError(f"{origin}: not a {RECORDING_FORMAT} artifact")
    version = header.get("v")
    if version != RECORDING_VERSION:
        raise ValueError(
            f"{origin}: recording version {version!r} not supported "
            f"(this build reads v{RECORDING_VERSION})"
        )
    rec = Recording(
        version=version,
        recorded_unix=float(header.get("recorded_unix", 0.0)),
        note=str(header.get("note", "")),
    )
    for lineno, line in enumerate(fh, start=2):
        if not line.strip():
            continue
        entry = json.loads(line)
        if entry.get("kind") != "request":
            continue  # forward-compat: unknown kinds skipped, not fatal
        if entry.get("status") == "error":
            err = entry.get("error") or {}
            rec.exchanges.append(
                Exchange(
                    t_rel=float(entry["t"]),
                    path=entry["path"],
                    error=(str(err.get("message", "")), err.get("status")),
                )
            )
        else:
            rec.exchanges.append(
                Exchange(
                    t_rel=float(entry["t"]),
                    path=entry["path"],
                    response=entry.get("response"),
                )
            )
    return rec


class ReplaySource:
    """A Transport that answers from a :class:`Recording`.

    Recorded errors re-raise as :class:`ApiError` with the recorded
    message/status; a path the recording never saw raises ApiError 404
    (the same shape an apiserver gives for an absent resource), so a
    replay run can never silently invent data.
    """

    def __init__(
        self,
        recording: Recording,
        *,
        clock: Callable[[], float] | None = None,
        rate: float = 1.0,
    ) -> None:
        if rate <= 0:
            raise ValueError("rate must be > 0")
        self.recording = recording
        self.rate = rate
        self._clock = clock  # injected monotonic; None → sequential mode
        self._t0: float | None = None
        self._lock = threading.Lock()
        self._by_path: dict[str, list[Exchange]] = {}
        for ex in recording.exchanges:
            self._by_path.setdefault(ex.path, []).append(ex)
        self._cursor: dict[str, int] = {}
        self.requests_served = 0
        self.requests_unknown = 0

    def _elapsed(self) -> float:
        now = self._clock()  # type: ignore[misc] — timed mode only
        if self._t0 is None:
            self._t0 = now
        return (now - self._t0) * self.rate

    def _pick(self, entries: list[Exchange], path: str) -> Exchange:
        if self._clock is None:
            # Sequential mode: recorded order per path, stick at last.
            i = self._cursor.get(path, 0)
            self._cursor[path] = min(i + 1, len(entries) - 1)
            return entries[i]
        # Timed mode: newest exchange whose offset has elapsed.
        horizon = self._elapsed()
        chosen = entries[0]
        for ex in entries:
            if ex.t_rel <= horizon:
                chosen = ex
            else:
                break
        return chosen

    def request(self, path: str, timeout_s: float = 2.0) -> Any:
        with self._lock:
            entries = self._by_path.get(path)
            if not entries:
                self.requests_unknown += 1
                raise ApiError(path, "not present in recording", 404)
            ex = self._pick(entries, path)
            self.requests_served += 1
        if ex.error is not None:
            raise ApiError(path, ex.error[0], ex.error[1])
        # Deep-copy via the JSON round trip: replayed responses must be
        # as mutation-isolated as freshly parsed wire responses.
        return json.loads(json.dumps(ex.response))
