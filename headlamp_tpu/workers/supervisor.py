"""Supervisor: one leader process, N serving workers (ADR-029 part 5).

The supervisor owns the only cluster-facing ``DashboardApp``: it syncs,
publishes every generation through a :class:`SegmentBusPublisher`
(segment + bus backlog in one call), and serves the NDJSON bus on an
internal loopback port — the fallback rung and the cross-host wire
format, unchanged. The workers it forks never touch the cluster: each
is a ``ReplicaApp`` behind :func:`~.worker.worker_main`, fed from the
segment, accepting on the public port via SO_REUSEPORT or the shared
pre-bound listener.

Fork, not spawn, deliberately: the listener fd and the segment path
must reach the children, and fork inherits both without pickling. The
supervisor forks BEFORE its first sync (no jax, no device handles, no
thread pools yet), which is what makes fork safe here — the children
import their own runtime stacks fresh.
"""

from __future__ import annotations

import multiprocessing
import os
import threading
import time
from typing import Any, Callable

from .balancer import pick_strategy, shared_listener
from .shm import SegmentBusPublisher, SnapshotSegment, default_segment_path
from .status import WorkerStatusBoard
from .worker import worker_main

#: Default supervisor sync cadence — the background heartbeat every
#: worker's generation feed rides on.
DEFAULT_SYNC_INTERVAL_S = 2.0


class WorkerSupervisor:
    """Builds the leader app, publishes into the shared-memory plane,
    and keeps N worker processes accepting on the public port.

    ``app_factory`` returns the cluster-facing ``DashboardApp`` (demo
    transport, kube proxy, in-cluster — the supervisor is
    transport-agnostic). Lifecycle: ``start()`` forks workers and
    starts the sync loop; ``poll()`` reports liveness; ``stop()``
    terminates children and closes the plane.
    """

    def __init__(
        self,
        app_factory: Callable[[], Any],
        *,
        host: str = "127.0.0.1",
        port: int = 8631,
        workers: int = 2,
        segment_path: str | None = None,
        board_path: str | None = None,
        sync_interval_s: float = DEFAULT_SYNC_INTERVAL_S,
        strategy: str | None = None,
    ) -> None:
        if workers < 1:
            raise ValueError("workers must be >= 1")
        self._app_factory = app_factory
        self.host = host
        self.port = int(port)
        self.workers = int(workers)
        self.segment_path = segment_path or default_segment_path(port)
        self.board_path = board_path or default_segment_path(port, kind="wsb")
        self.sync_interval_s = sync_interval_s
        self.strategy = strategy or pick_strategy()
        self.app: Any = None
        self.publisher: SegmentBusPublisher | None = None
        self.segment: SnapshotSegment | None = None
        self.board: WorkerStatusBoard | None = None
        self.bus_url: str | None = None
        self._bus_server: Any = None
        self._listener: Any = None
        self._procs: list[Any] = []

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        """Bring the plane up in dependency order: segment + board
        first (workers attach at entry), then the fork — BEFORE the
        leader app exists, so children inherit no jax/device state —
        then the leader app, its internal bus endpoint, and the sync
        heartbeat."""
        self.segment = SnapshotSegment(self.segment_path)
        self.board = WorkerStatusBoard.create(self.board_path, n_slots=self.workers)
        listener = None
        if self.strategy != "reuseport":
            listener = shared_listener(self.host, self.port)
            self._listener = listener
        # The internal bus endpoint's port must be known before the
        # fork so workers get their fallback URL; bind it now, serve
        # after the leader app exists.
        import socket as _socket

        bus_sock = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
        bus_sock.bind((self.host, 0))
        bus_port = bus_sock.getsockname()[1]
        bus_sock.close()
        self.bus_url = f"http://{self.host}:{bus_port}"
        ctx = multiprocessing.get_context("fork")
        for worker_id in range(self.workers):
            proc = ctx.Process(
                target=worker_main,
                args=(worker_id, self.host, self.port),
                kwargs={
                    "segment_path": self.segment_path,
                    "board_path": self.board_path,
                    "fallback_url": self.bus_url,
                    "listen_socket": listener,
                },
                daemon=True,
                name=f"headlamp-worker-{worker_id}",
            )
            proc.start()
            self._procs.append(proc)
        # Leader app + publisher, post-fork.
        app = self._app_factory()
        self.app = app
        self.publisher = SegmentBusPublisher(
            self.segment,
            ledger=getattr(app, "ledger", None),
            note=f"supervisor {self.host}:{self.port}",
        )
        app.replication = self.publisher
        bus_server = app.serve(self.host, bus_port)
        self._bus_server = bus_server
        bus_thread = threading.Thread(
            target=bus_server.serve_forever,
            name="workers-supervisor-bus",
            daemon=True,
        )
        bus_thread.start()
        app.start_background_sync(self.sync_interval_s)

    def poll(self) -> dict[str, Any]:
        """Liveness + plane counters — the supervisor-side triage view
        (workers expose their own /healthz on the public port)."""
        alive = [p.pid for p in self._procs if p.is_alive()]
        out: dict[str, Any] = {
            "strategy": self.strategy,
            "workers": self.workers,
            "alive": len(alive),
            "pids": alive,
            "segment_path": self.segment_path,
        }
        if self.publisher is not None:
            out["replication"] = self.publisher.snapshot()
        if self.board is not None:
            out["board"] = self.board.snapshot()
        return out

    def wait(self) -> None:
        """Park the supervisor's main thread until interrupted —
        ``python -m headlamp_tpu.server --workers N``'s steady state."""
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:  # analysis: disable=EXC001
            pass  # top-of-process Ctrl-C: clean stop IS the handling

    def stop(self, *, unlink: bool = True) -> None:
        for proc in self._procs:
            if proc.is_alive():
                proc.terminate()
        for proc in self._procs:
            proc.join(timeout=5.0)
        self._procs.clear()
        if self._bus_server is not None:
            try:
                self._bus_server.shutdown()
                self._bus_server.server_close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
            self._bus_server = None
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
        if self.segment is not None:
            self.segment.close()
            if unlink:
                self.segment.unlink()
        if self.board is not None:
            self.board.close()
            if unlink:
                self.board.unlink()


def run_supervisor(
    app_factory: Callable[[], Any],
    *,
    host: str,
    port: int,
    workers: int,
    sync_interval_s: float = DEFAULT_SYNC_INTERVAL_S,
) -> None:
    """CLI entry (``--workers N``): start, announce, park, clean up."""
    sup = WorkerSupervisor(
        app_factory,
        host=host,
        port=port,
        workers=workers,
        sync_interval_s=sync_interval_s,
    )
    sup.start()
    print(
        f"TPU dashboard SUPERVISOR: {workers} workers on "
        f"http://{host}:{port}/tpu ({sup.strategy}; pid {os.getpid()})"
    )
    try:
        sup.wait()
    finally:
        sup.stop()


__all__ = ["DEFAULT_SYNC_INTERVAL_S", "WorkerSupervisor", "run_supervisor"]
