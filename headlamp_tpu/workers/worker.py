"""Worker process: ReplicaApp fed from the segment (ADR-029 part 2).

A worker is the ADR-025 replica, re-hosted: the same ``ReplicaApp``,
the same ``apply_record`` seam, the same stale-honesty wiring — only
the FEED changes. :class:`ShmConsumer` polls the shared-memory segment
(a header peek per tick, a full seqlock read only on generation
change) and falls down the counted NDJSON-bus ladder when the segment
is missing, version-gated, or corrupt. Because the segment carries the
canonical bus record line verbatim, a segment-applied generation is
byte-identical — pages, ETags, 304s, SSE frames — to a bus-applied
one; the fast path changes WHERE the bytes come from, never what they
decode to.

The shm win on top of skipping the HTTP hop: the segment ships the
ADR-012 columns pre-encoded, so after ``apply_record`` the consumer
SEEDS the device fleet cache directly (``DeviceFleetCache.seed``) and
the worker's first render of the generation skips ``encode_fleet``'s
per-node Python loop entirely.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

from ..replicate.bus import _BYTES, parse_payload
from ..replicate.replica import ReplicaApp, set_active_consumer
from .shm import SegmentError, SegmentReader, SegmentUnavailable
from .status import WorkerSlot


class ShmConsumer:
    """Pulls generations off the shared-memory segment into one
    ReplicaApp, with the NDJSON bus as the counted fallback.
    ``poll_once`` is the whole protocol — deterministic tests call it
    directly; production calls ``start()`` for a poll thread (a
    sanctioned THR001 seam, mirroring ``BusConsumer``). Every failure
    rung is absorbed and counted: a missing supervisor must degrade the
    worker to stale-honest serving, never crash it."""

    def __init__(
        self,
        app: ReplicaApp,
        segment_path: str,
        *,
        fallback_fetch: Callable[[int], str] | None = None,
        slot: WorkerSlot | None = None,
        monotonic: Callable[[], float] | None = None,
        interval_s: float = 0.25,
    ) -> None:
        self.app = app
        self.segment_path = segment_path
        self._fallback = fallback_fetch
        self.slot = slot
        self._mono = monotonic or time.monotonic
        self.interval_s = interval_s
        self._reader: SegmentReader | None = None
        self.polls = 0
        self.applied_shm = 0
        self.applied_fallback = 0
        self.attach_failures = 0
        self.fallback_failures = 0
        self.cursor = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # The /healthz runtime.replication block reads the consumer —
        # same wiring as BusConsumer, role "worker".
        app.replication = self
        set_active_consumer(self)

    # -- one tick --------------------------------------------------------

    def poll_once(self) -> int:
        """One tick of the fallback ladder: segment read → apply →
        seed; any segment failure counts an attach failure and (when a
        bus fetch is wired) drops to the NDJSON path. Returns the
        number of generations applied."""
        self.polls += 1
        frame = None
        segment_ok = False
        try:
            reader = self._reader
            if reader is None:
                reader = SegmentReader(self.segment_path)
                self._reader = reader
            if reader.generation() > self.app.snapshot_generation():
                frame = reader.read()
            segment_ok = True
        except SegmentError:
            # Missing / version-gated / corrupt: drop the attachment so
            # the next tick re-opens (the file may be replaced by a
            # fresh supervisor), count the rung, take the ladder.
            self._drop_reader()
            self.attach_failures += 1
            if self.slot is not None:
                self.slot.attach_failure()
        if frame is not None:
            try:
                record = frame.record()
            except ValueError:
                # Parseable segment, unparseable record: same rung as
                # corrupt — counted, then the bus gets a chance.
                self._drop_reader()
                self.attach_failures += 1
                segment_ok = False
                record = None
                if self.slot is not None:
                    self.slot.attach_failure()
            if record is not None:
                generation = int(record.get("generation") or 0)
                self.cursor = max(self.cursor, generation)
                if self.app.apply_record(record):
                    self.applied_shm += 1
                    if self.slot is not None:
                        self.slot.applied(generation)
                    self._seed_columns(frame.columns, generation)
                    return 1
                return 0
        if segment_ok:
            # Segment healthy and nothing newer than the app: done.
            return 0
        return self._poll_fallback()

    def _drop_reader(self) -> None:
        reader, self._reader = self._reader, None
        if reader is not None:
            try:
                reader.close()
            except Exception:  # noqa: BLE001 — teardown of a broken map must not mask the rung
                pass

    def _seed_columns(self, columns: dict[str, Any], generation: int) -> None:
        """Install the segment's pre-encoded ADR-012 columns so the
        first render skips encode_fleet. Absorbed: a seeding failure
        costs the render-path encode it would have skipped, nothing
        else."""
        try:
            from ..runtime.device_cache import fleet_cache

            for provider, fleet in columns.items():
                fleet_cache.seed(provider, generation, fleet)
        except Exception:  # noqa: BLE001 — seeding is an optimization only
            pass

    def _poll_fallback(self) -> int:
        """The NDJSON-bus rung: a BusConsumer-shaped pull through the
        injected fetch (absent on segment-only topologies)."""
        if self._fallback is None:
            return 0
        try:
            payload = self._fallback(self.cursor)
            _, records = parse_payload(payload, origin="<worker-fallback>")
        except Exception:  # noqa: BLE001 — dead leader degrades, never crashes
            self.fallback_failures += 1
            return 0
        _BYTES.inc(len(payload), role="applied")
        applied = 0
        for record in records:
            generation = int(record.get("generation") or 0)
            if self.app.apply_record(record):
                applied += 1
                self.applied_fallback += 1
                if self.slot is not None:
                    self.slot.applied(generation)
                    self.slot.fallback_decode()
            self.cursor = max(self.cursor, generation)
        return applied

    # -- poll thread (sanctioned THR001 seam) ----------------------------

    def start(self, interval_s: float | None = None) -> None:
        if self._thread is not None:
            return
        interval = interval_s if interval_s is not None else self.interval_s
        self._stop.clear()

        def _consume_loop() -> None:
            while not self._stop.is_set():
                try:
                    self.poll_once()
                except Exception:  # noqa: BLE001 — keep pulling
                    pass
                self._stop.wait(interval)

        thread = threading.Thread(
            target=_consume_loop, name="workers-shm-consumer", daemon=True
        )
        self._thread = thread
        thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=2.0)
            self._thread = None

    def snapshot(self) -> dict[str, Any]:
        """The /healthz ``runtime.replication`` block (worker role)."""
        app = self.app
        lag = app.lag_s()
        return {
            "role": "worker",
            "segment_path": self.segment_path,
            "segment_attached": self._reader is not None,
            "cursor": self.cursor,
            "last_generation": app.snapshot_generation(),
            "applied": app.applied,
            "applied_shm": self.applied_shm,
            "applied_fallback": self.applied_fallback,
            "attach_failures": self.attach_failures,
            "fallback_failures": self.fallback_failures,
            "rejected_stale": app.rejected_stale,
            "polls": self.polls,
            "stale": app.stale(),
            "lag_s": round(lag, 3) if lag is not None else None,
        }


def worker_main(
    worker_id: int,
    host: str,
    port: int,
    *,
    segment_path: str,
    board_path: str,
    fallback_url: str | None = None,
    listen_socket: Any = None,
    interval_s: float = 0.25,
) -> None:
    """Process entry for one serving worker: ReplicaApp + segment
    consumer + per-worker observability, accepting on the shared port
    (via the inherited ``listen_socket`` when the supervisor chose the
    fd-passing strategy, via SO_REUSEPORT otherwise). Runs until the
    process is terminated — the supervisor owns lifecycle."""
    from ..push.hub import set_worker_identity
    from ..replicate.replica import pool_fetch
    from .status import WorkerStatusBoard, register_worker_metrics

    app = ReplicaApp()
    slot = None
    try:
        board = WorkerStatusBoard.attach(board_path)
        slot = board.slot(worker_id)
        register_worker_metrics(board)
        app.workers = _BoardHealth(board, worker_id)
    except Exception:  # noqa: BLE001 — a lost board degrades observability, not serving
        board = None
    set_worker_identity(f"w{worker_id}")
    fetch = pool_fetch(fallback_url) if fallback_url else None
    consumer = ShmConsumer(
        app,
        segment_path,
        fallback_fetch=fetch,
        slot=slot,
        interval_s=interval_s,
    )
    consumer.poll_once()  # best-effort first fill before the socket opens
    consumer.start()
    server = app.serve(
        host,
        port,
        reuse_port=listen_socket is None,
        listen_socket=listen_socket,
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:  # analysis: disable=EXC001
        pass  # supervisor-initiated stop: clean exit IS the handling
    finally:
        consumer.stop()


class _BoardHealth:
    """Adapter giving /healthz its ``runtime.workers`` block: the whole
    board, stamped with which worker answered."""

    def __init__(self, board: Any, worker_id: int) -> None:
        self._board = board
        self._worker_id = worker_id

    def snapshot(self) -> dict[str, Any]:
        return self._board.snapshot(self_id=self._worker_id)


__all__ = ["ShmConsumer", "worker_main"]
