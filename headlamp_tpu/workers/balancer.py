"""One port, N accepting processes (ADR-029 part 4: the front door).

Strategy ladder:

1. ``SO_REUSEPORT`` — each worker binds the same ``(host, port)`` and
   the kernel load-balances accepts. Zero in-repo moving parts; Linux
   and the BSDs offer it.
2. fd passing — the supervisor binds ONE listening socket before
   forking and every worker inherits the fd (:func:`shared_listener`);
   the kernel wakes one accepter per connection. Works on any POSIX
   host, at the price of a shared accept queue.
3. :class:`RoundRobinBalancer` — a plain round-robin TCP proxy for
   topologies where the workers had to bind distinct ports (no fork
   relationship, e.g. pre-started workers in a test). In-repo so the
   bench works everywhere; never the production default.

All three present the same contract to clients: one address, and any
accepted connection is PINNED to one worker for its lifetime — which
is exactly what keeps SSE streams per-worker (ADR-021 resume semantics
ride ``Last-Event-ID``, so a reconnect landing on a different worker
replays from its hub or falls back to a full paint, unchanged).
"""

from __future__ import annotations

import socket
import threading
from typing import Any


def reuseport_supported() -> bool:
    """Does this host offer SO_REUSEPORT? Probed by actually setting
    the option on a throwaway socket — the constant existing does not
    mean the kernel accepts it (WSL1, some container runtimes)."""
    if not hasattr(socket, "SO_REUSEPORT"):
        return False
    probe = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        probe.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        return True
    except OSError:
        return False
    finally:
        probe.close()


def pick_strategy() -> str:
    """``"reuseport"`` where the kernel offers it, ``"fd-passing"``
    otherwise — the supervisor's default choice."""
    return "reuseport" if reuseport_supported() else "fd-passing"


def shared_listener(host: str, port: int, *, backlog: int = 128) -> socket.socket:
    """The fd-passing strategy's one listening socket: bound and
    listening BEFORE workers fork, inheritable across the fork so every
    worker accepts on the same queue."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    sock.bind((host, port))
    sock.listen(backlog)
    sock.set_inheritable(True)
    return sock


class RoundRobinBalancer:
    """Minimal round-robin TCP proxy: accept on one port, pin each
    accepted connection to the next backend, pump bytes both ways until
    either side closes. Thread-per-direction — acceptable for the
    fallback tier it is (the bench and odd topologies), not a
    production data plane."""

    def __init__(
        self,
        host: str,
        port: int,
        backends: list[tuple[str, int]],
        *,
        backlog: int = 128,
    ) -> None:
        if not backends:
            raise ValueError("balancer needs at least one backend")
        self.backends = list(backends)
        self._next = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self.connections = 0
        self.failures = 0
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(backlog)
        self.address = self._sock.getsockname()[:2]

    def pick(self) -> tuple[str, int]:
        with self._lock:
            backend = self.backends[self._next % len(self.backends)]
            self._next += 1
            self.connections += 1
        return backend

    # -- serving (sanctioned THR001 seam: RoundRobinBalancer.start) -----

    def start(self) -> None:
        if self._threads:
            return
        self._stop.clear()
        self._sock.settimeout(0.2)

        def _pump(src: socket.socket, dst: socket.socket) -> None:
            try:
                while True:
                    chunk = src.recv(65536)
                    if not chunk:
                        break
                    dst.sendall(chunk)
            except OSError:
                pass  # either side closing ends the stream — normal
            finally:
                for s in (src, dst):
                    try:
                        s.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass

        def _accept_loop() -> None:
            while not self._stop.is_set():
                try:
                    client, _addr = self._sock.accept()
                except socket.timeout:
                    continue
                except OSError:
                    return  # listener closed: stop()
                host, port = self.pick()
                try:
                    upstream = socket.create_connection((host, port), timeout=5.0)
                except OSError:
                    self.failures += 1
                    client.close()
                    continue
                for pair in ((client, upstream), (upstream, client)):
                    t = threading.Thread(target=_pump, args=pair, daemon=True)
                    t.start()

        accepter = threading.Thread(
            target=_accept_loop, name="workers-balancer", daemon=True
        )
        self._threads.append(accepter)
        accepter.start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads.clear()

    def snapshot(self) -> dict[str, Any]:
        return {
            "backends": [f"{h}:{p}" for h, p in self.backends],
            "connections": self.connections,
            "failures": self.failures,
        }


__all__ = [
    "RoundRobinBalancer",
    "pick_strategy",
    "reuseport_supported",
    "shared_listener",
]
