"""Multi-process serving tier (ADR-029).

N single-threaded-serving worker PROCESSES accept on one port — via
``SO_REUSEPORT`` where the kernel offers it, or a shared pre-bound
listener (fd passing over fork) everywhere else — each running a
:class:`~headlamp_tpu.replicate.replica.ReplicaApp` fed through the
existing ``apply_record`` seam. Same-host snapshot distribution rides
a shared-memory segment (generation header + seqlock ready flag +
ADR-012 columns + the canonical NDJSON record); the NDJSON bus stays
the cross-host wire format and the counted fallback.
"""

from .balancer import (
    RoundRobinBalancer,
    pick_strategy,
    reuseport_supported,
    shared_listener,
)
from .shm import (
    SEGMENT_VERSION,
    SegmentBusPublisher,
    SegmentCorrupt,
    SegmentError,
    SegmentFrame,
    SegmentReader,
    SegmentUnavailable,
    SegmentVersionGated,
    SnapshotSegment,
    default_segment_path,
)
from .status import WorkerStatusBoard, register_worker_metrics
from .supervisor import WorkerSupervisor, run_supervisor
from .worker import ShmConsumer, worker_main

__all__ = [
    "RoundRobinBalancer",
    "SEGMENT_VERSION",
    "SegmentBusPublisher",
    "SegmentCorrupt",
    "SegmentError",
    "SegmentFrame",
    "SegmentReader",
    "SegmentUnavailable",
    "SegmentVersionGated",
    "ShmConsumer",
    "SnapshotSegment",
    "WorkerStatusBoard",
    "WorkerSupervisor",
    "default_segment_path",
    "pick_strategy",
    "register_worker_metrics",
    "reuseport_supported",
    "run_supervisor",
    "shared_listener",
    "worker_main",
]
