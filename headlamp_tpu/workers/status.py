"""Cross-worker status board (ADR-029 part 3: observability).

Per-worker monotone counters in a second tiny mmap'd file: each worker
owns ONE fixed slot it alone writes (no lock — single writer per
slot), and any process on the host (another worker answering its
/metricsz scrape, the supervisor's health poll, the bench) reads all
slots. This is what makes "per-worker metrics aggregation on
/metricsz" work under SO_REUSEPORT, where a scrape lands on an
arbitrary worker: every worker renders every worker's counters from
the shared board, so the scrape answer does not depend on which
process accepted the socket.

Layout::

    0   magic    8s  b"HLTPWSB\\0"
    8   version  u32
    12  n_slots  u32
    16  slots, 48 bytes each:
        u32 worker_id   u32 pid   u64 generation
        u64 generations_applied   u64 shm_attach_failures
        u64 fallback_decodes

Slot reads are not seqlock-guarded: every field is independently
monotone (or a pid/id that never changes after registration), so a
torn read can only show a value between two true values — fine for
counters, and the price of guarding would be a lock shared across
processes.
"""

from __future__ import annotations

import mmap
import os
import struct
import tempfile
from typing import Any

from ..obs.metrics import registry as _metrics_registry

BOARD_MAGIC = b"HLTPWSB\x00"
BOARD_VERSION = 1
_BOARD_HEADER = struct.Struct("<8sII")
_SLOT = struct.Struct("<IIQQQQ")
SLOT_SIZE = _SLOT.size  # 48

#: The three ISSUE-named per-worker families (counters, per-worker
#: label), rendered at scrape time from the shared board.
WORKER_METRIC_FAMILIES = (
    "headlamp_tpu_worker_generations_applied_total",
    "headlamp_tpu_worker_shm_attach_failures_total",
    "headlamp_tpu_worker_fallback_decodes_total",
)


class WorkerSlot:
    """One worker's writer handle: counters live as plain ints here and
    every mutation writes the packed slot through — the board is the
    publication, the ints are the fast local truth."""

    def __init__(self, board: "WorkerStatusBoard", worker_id: int) -> None:
        self._board = board
        self.worker_id = int(worker_id)
        self.pid = os.getpid()
        self.generation = 0
        self.generations_applied = 0
        self.shm_attach_failures = 0
        self.fallback_decodes = 0
        self._write()

    def _write(self) -> None:
        self._board._write_slot(
            self.worker_id,
            self.pid,
            self.generation,
            self.generations_applied,
            self.shm_attach_failures,
            self.fallback_decodes,
        )

    def applied(self, generation: int) -> None:
        self.generation = int(generation)
        self.generations_applied += 1
        self._write()

    def attach_failure(self) -> None:
        self.shm_attach_failures += 1
        self._write()

    def fallback_decode(self) -> None:
        self.fallback_decodes += 1
        self._write()


class WorkerStatusBoard:
    """The mmap'd board. ``create`` (supervisor) zeroes fresh slots via
    atomic temp-file + rename; ``attach`` (workers, scrapers) maps the
    existing file writable so each worker can publish its own slot."""

    def __init__(self, path: str, *, n_slots: int, _map: mmap.mmap, _file: Any) -> None:
        self.path = path
        self.n_slots = int(n_slots)
        self._map = _map
        self._file = _file

    @classmethod
    def create(cls, path: str, *, n_slots: int) -> "WorkerStatusBoard":
        size = _BOARD_HEADER.size + int(n_slots) * SLOT_SIZE
        directory = os.path.dirname(path) or "."
        fd, tmp = tempfile.mkstemp(prefix=".hltp-wsb-", dir=directory)
        try:
            os.ftruncate(fd, size)
            header = bytearray(_BOARD_HEADER.size)
            _BOARD_HEADER.pack_into(header, 0, BOARD_MAGIC, BOARD_VERSION, n_slots)
            os.pwrite(fd, bytes(header), 0)
            file = os.fdopen(os.dup(fd), "r+b")
            os.replace(tmp, path)
        except BaseException:
            os.close(fd)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        os.close(fd)
        return cls(path, n_slots=n_slots, _map=mmap.mmap(file.fileno(), size), _file=file)

    @classmethod
    def attach(cls, path: str) -> "WorkerStatusBoard":
        file = open(path, "r+b")
        try:
            size = os.fstat(file.fileno()).st_size
            m = mmap.mmap(file.fileno(), size)
            magic, version, n_slots = _BOARD_HEADER.unpack_from(m, 0)
            if magic != BOARD_MAGIC or version != BOARD_VERSION:
                m.close()
                raise ValueError(f"foreign status board at {path}")
        except BaseException:
            file.close()
            raise
        return cls(path, n_slots=n_slots, _map=m, _file=file)

    # -- slot I/O --------------------------------------------------------

    def slot(self, worker_id: int) -> WorkerSlot:
        if not 0 <= int(worker_id) < self.n_slots:
            raise ValueError(f"worker id {worker_id} outside board ({self.n_slots} slots)")
        return WorkerSlot(self, worker_id)

    def _write_slot(self, worker_id: int, *values: int) -> None:
        offset = _BOARD_HEADER.size + int(worker_id) * SLOT_SIZE
        _SLOT.pack_into(self._map, offset, int(worker_id), *[int(v) for v in values])

    def rows(self) -> list[dict[str, int]]:
        """Every REGISTERED slot (pid != 0), in worker-id order."""
        out: list[dict[str, int]] = []
        for i in range(self.n_slots):
            offset = _BOARD_HEADER.size + i * SLOT_SIZE
            worker_id, pid, generation, applied, attach_failures, fallbacks = (
                _SLOT.unpack_from(self._map, offset)
            )
            if pid == 0:
                continue
            out.append(
                {
                    "worker": worker_id,
                    "pid": pid,
                    "generation": generation,
                    "generations_applied": applied,
                    "shm_attach_failures": attach_failures,
                    "fallback_decodes": fallbacks,
                }
            )
        return out

    def samples(self, field: str) -> list[tuple[tuple[str, ...], int]]:
        """((worker,), value) pairs for one counter field — the
        scrape-time feed of the per-worker metric families."""
        return [((f"w{row['worker']}",), row[field]) for row in self.rows()]

    def snapshot(self, *, self_id: int | None = None) -> dict[str, Any]:
        """The /healthz ``runtime.workers`` block: which worker
        answered, how many slots are live, and every worker's counters
        (the whole board — triage must not depend on which worker the
        probe landed on)."""
        rows = self.rows()
        return {
            "self": f"w{self_id}" if self_id is not None else None,
            "slots": self.n_slots,
            "live": len(rows),
            "workers": rows,
        }

    def close(self) -> None:
        try:
            self._map.close()
        finally:
            self._file.close()

    def unlink(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass


def register_worker_metrics(board: WorkerStatusBoard) -> None:
    """Wire the three per-worker counter families to ``board`` —
    callback counters rendered from the shared slots at scrape time,
    with latest-producer-wins re-registration (same contract as
    ``gauge_fn``) so every process that attaches the board can call
    this idempotently."""
    _metrics_registry.counter_samples_fn(
        "headlamp_tpu_worker_generations_applied_total",
        "Snapshot generations applied by each worker process (from the "
        "shared status board; labeled by worker slot).",
        ("worker",),
        lambda: board.samples("generations_applied"),
    )
    _metrics_registry.counter_samples_fn(
        "headlamp_tpu_worker_shm_attach_failures_total",
        "Shared-memory segment attach/read failures per worker (each one "
        "is a counted drop down the ADR-029 fallback ladder).",
        ("worker",),
        lambda: board.samples("shm_attach_failures"),
    )
    _metrics_registry.counter_samples_fn(
        "headlamp_tpu_worker_fallback_decodes_total",
        "Generations a worker applied via the NDJSON bus fallback "
        "instead of the shared-memory segment.",
        ("worker",),
        lambda: board.samples("fallback_decodes"),
    )


__all__ = [
    "BOARD_MAGIC",
    "BOARD_VERSION",
    "SLOT_SIZE",
    "WORKER_METRIC_FAMILIES",
    "WorkerSlot",
    "WorkerStatusBoard",
    "register_worker_metrics",
]
