"""Shared-memory snapshot plane (ADR-029 part 1: the segment).

One mmap'd file-backed segment per serving host, published by the
supervisor (the leader/consumer process) and attached read-only by
every worker. Layout::

    0   magic            8s   b"HLTPSHM\\0"
    8   version          u32  SEGMENT_VERSION (readers refuse others)
    12  reserved         u32
    16  seq              u64  seqlock: odd = write in progress
    24  generation       u64  snapshot generation of the payload
    32  fencing          u64  leadership term that published it
    40  record_off       u64  canonical NDJSON record (bus codec line)
    48  record_len       u64
    56  columns_off      u64  per-provider ADR-012 packed columns
    64  columns_len      u64
    128 payload area

Seqlock protocol (the "ready flag" of the ISSUE): the writer bumps
``seq`` to an odd value, writes payload then header fields, then bumps
``seq`` to the next even value. A reader snapshots ``seq`` (retrying
while odd), COPIES the payload bytes out of the mmap, re-reads ``seq``,
and only parses when the two reads match — so a torn write can cost a
retry, never a half-applied snapshot. CPython's mmap stores are not
atomic instructions, but the protocol only needs "a concurrent write
is detectable", which the double-read gives: any interleaving either
leaves ``seq`` odd or changes it between the reads.

The NDJSON record inside the segment is the EXACT line the bus
publisher retains (``replicate.bus.dumps_record`` bytes) — one codec,
two transports — so a record applied from the segment is
indistinguishable from one applied off the bus, and every byte-identity
property of ADR-025 (ETags, 304s, push frames) carries over for free.

Fallback ladder (ADR-029): segment missing → ``SegmentUnavailable``;
foreign/future layout → ``SegmentVersionGated``; truncated header, bad
magic, unstable seqlock, payload that fails to parse →
``SegmentCorrupt``. Workers count each rung and drop to the NDJSON bus
(the cross-host wire format, unchanged), never serve garbage.
"""

from __future__ import annotations

import json
import mmap
import os
import struct
import tempfile
from dataclasses import dataclass
from typing import TYPE_CHECKING, Any

from ..replicate.bus import BusPublisher
from ..runtime.columns import pack_fleet, unpack_fleet

if TYPE_CHECKING:  # pragma: no cover - typing only
    from ..analytics.encode import FleetArrays

SEGMENT_MAGIC = b"HLTPSHM\x00"
SEGMENT_VERSION = 1

#: Fixed header area; payload starts here. Generous so the header can
#: grow fields without moving the payload across versions.
HEADER_SIZE = 128

#: Default segment size. The 1024-node fixture's self-contained record
#: is a few MB; 64 MiB of file-backed mmap is virtual until written and
#: leaves headroom for the ROADMAP's 16k-fleet item. A payload that
#: does not fit is refused (publish returns False, counted) — workers
#: then ride the NDJSON bus, which has no size ceiling.
DEFAULT_SEGMENT_SIZE = 64 * 1024 * 1024

_HEADER = struct.Struct("<8sII7Q")  # magic, version, reserved, seq..columns_len
_SEQ = struct.Struct("<Q")
_SEQ_OFF = 16
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")


class SegmentError(Exception):
    """Base of the fallback-ladder rungs."""


class SegmentUnavailable(SegmentError):
    """No segment at the path (supervisor not running / not publishing)."""


class SegmentVersionGated(SegmentError):
    """Segment exists but speaks a different layout version."""


class SegmentCorrupt(SegmentError):
    """Bad magic, truncated payload, or an unstable seqlock read."""


def default_segment_path(port: int, *, kind: str = "seg") -> str:
    """Per-port rendezvous path: /dev/shm where the host has it (true
    shared memory, zero disk traffic), the tempdir otherwise — both
    sides derive the same path from the serving port alone."""
    base = "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()
    return os.path.join(base, f"headlamp-tpu-{int(port)}.{kind}")


def _pack_columns(columns: dict[str, "FleetArrays"]) -> bytes:
    """Per-provider packed columns: u32 count, then per provider a
    u32-length-prefixed utf-8 name and a u64-length-prefixed
    ``pack_fleet`` blob."""
    parts = [_U32.pack(len(columns))]
    for name in sorted(columns):
        blob = pack_fleet(columns[name])
        encoded = name.encode("utf-8")
        parts.append(_U32.pack(len(encoded)))
        parts.append(encoded)
        parts.append(_U64.pack(len(blob)))
        parts.append(blob)
    return b"".join(parts)


def _unpack_columns(buf: bytes) -> dict[str, "FleetArrays"]:
    out: dict[str, "FleetArrays"] = {}
    view = memoryview(buf)
    if len(view) < _U32.size:
        raise ValueError("columns section truncated")
    (count,) = _U32.unpack_from(view, 0)
    pos = _U32.size
    for _ in range(count):
        (name_len,) = _U32.unpack_from(view, pos)
        pos += _U32.size
        name = bytes(view[pos : pos + name_len]).decode("utf-8")
        pos += name_len
        (blob_len,) = _U64.unpack_from(view, pos)
        pos += _U64.size
        if pos + blob_len > len(view):
            raise ValueError(f"columns section truncated in {name!r}")
        out[name] = unpack_fleet(bytes(view[pos : pos + blob_len]))
        pos += blob_len
    return out


@dataclass
class SegmentFrame:
    """One stable read of the segment: the generation header plus the
    payload COPIED out of the mmap (the columns view bytes are owned by
    this frame, so a later publish can never mutate them under a
    reader)."""

    generation: int
    fencing: int
    record_line: str
    columns: dict[str, "FleetArrays"]

    def record(self) -> dict[str, Any]:
        """The canonical bus record (``json.loads`` of the one line) —
        feed it straight into ``ReplicaApp.apply_record``."""
        return json.loads(self.record_line)


class SnapshotSegment:
    """Writer half: the supervisor's publish target. Creation is
    atomic (temp file + rename), so a reader can never attach a
    half-initialized header."""

    def __init__(
        self,
        path: str,
        *,
        size: int = DEFAULT_SEGMENT_SIZE,
        version: int = SEGMENT_VERSION,
    ) -> None:
        self.path = path
        self.size = int(size)
        self.version = int(version)
        self.published = 0
        self.overflows = 0
        directory = os.path.dirname(path) or "."
        fd, tmp = tempfile.mkstemp(prefix=".hltp-seg-", dir=directory)
        try:
            os.ftruncate(fd, self.size)
            header = bytearray(HEADER_SIZE)
            _HEADER.pack_into(
                header, 0, SEGMENT_MAGIC, self.version, 0, 0, 0, 0, 0, 0, 0, 0
            )  # magic, version, reserved, seq, generation, fencing, 4 offsets/lens
            os.pwrite(fd, bytes(header), 0)
            self._file = os.fdopen(os.dup(fd), "r+b")
            os.replace(tmp, path)
        except BaseException:
            os.close(fd)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        os.close(fd)
        self._map = mmap.mmap(self._file.fileno(), self.size)
        self._seq = 0

    # -- publish ---------------------------------------------------------

    def publish(
        self,
        record_line: str,
        columns: dict[str, "FleetArrays"],
        *,
        generation: int,
        fencing: int = 0,
    ) -> bool:
        """Seqlock-guarded overwrite with the new generation. Returns
        False (counted) when the payload exceeds the segment — the
        caller's bus backlog still carries the generation, so workers
        fall back rather than stall."""
        record = record_line.encode("utf-8")
        cols = _pack_columns(columns)
        record_off = HEADER_SIZE
        columns_off = record_off + len(record) + (-len(record)) % 8
        if columns_off + len(cols) > self.size:
            self.overflows += 1
            return False
        m = self._map
        self._seq += 1  # odd: write in progress
        _SEQ.pack_into(m, _SEQ_OFF, self._seq)
        m[record_off : record_off + len(record)] = record
        m[columns_off : columns_off + len(cols)] = cols
        struct.pack_into(
            "<QQQQQQ",
            m,
            24,
            int(generation),
            int(fencing),
            record_off,
            len(record),
            columns_off,
            len(cols),
        )
        self._seq += 1  # even: stable
        _SEQ.pack_into(m, _SEQ_OFF, self._seq)
        self.published += 1
        return True

    def close(self) -> None:
        try:
            self._map.close()
        finally:
            self._file.close()

    def unlink(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass


class SegmentReader:
    """Reader half: workers attach read-only and pull stable frames.
    Construction raises the fallback-ladder rung that applies; ``read``
    re-checks the version every call (the file under the path can be
    replaced by a newer supervisor)."""

    #: Seqlock retries before declaring the segment unstable. Each
    #: retry is a microsecond-scale header re-read; 64 bounds a reader
    #: spinning against a pathological writer loop.
    MAX_RETRIES = 64

    def __init__(self, path: str) -> None:
        self.path = path
        try:
            self._file = open(path, "rb")
        except FileNotFoundError as exc:
            raise SegmentUnavailable(f"no segment at {path}") from exc
        try:
            size = os.fstat(self._file.fileno()).st_size
            if size < HEADER_SIZE:
                raise SegmentCorrupt(f"segment at {path} smaller than header")
            self._map = mmap.mmap(
                self._file.fileno(), size, access=mmap.ACCESS_READ
            )
        except SegmentError:
            self._file.close()
            raise
        except (OSError, ValueError) as exc:
            self._file.close()
            raise SegmentCorrupt(f"segment at {path} unmappable") from exc
        self._check_header()

    def _check_header(self) -> None:
        magic, version, _r, *_rest = _HEADER.unpack_from(self._map, 0)
        if magic != SEGMENT_MAGIC:
            raise SegmentCorrupt(
                f"segment at {self.path} has foreign magic {magic!r}"
            )
        if version != SEGMENT_VERSION:
            raise SegmentVersionGated(
                f"segment at {self.path} is layout v{version}; "
                f"this build reads v{SEGMENT_VERSION}"
            )

    def generation(self) -> int:
        """Cheap header peek — the poll loop's "anything new?" check
        (one u64 read, no payload copy). A mid-write peek may see the
        incoming generation early; the full ``read`` re-validates."""
        return _U64.unpack_from(self._map, 24)[0]

    def read(self) -> SegmentFrame | None:
        """One stable frame, or None while nothing has been published
        (generation 0). Raises ``SegmentVersionGated``/``SegmentCorrupt``
        per the fallback ladder."""
        self._check_header()
        m = self._map
        for _ in range(self.MAX_RETRIES):
            (seq1,) = _SEQ.unpack_from(m, _SEQ_OFF)
            if seq1 & 1:
                continue  # write in progress
            generation, fencing, record_off, record_len, cols_off, cols_len = (
                struct.unpack_from("<QQQQQQ", m, 24)
            )
            if generation == 0:
                return None
            end = max(record_off + record_len, cols_off + cols_len)
            if end > len(m) or record_off < HEADER_SIZE:
                raise SegmentCorrupt(
                    f"segment at {self.path} header points outside the map"
                )
            # Copy BEFORE the confirming seq read: the copy is what the
            # second read validates.
            record = bytes(m[record_off : record_off + record_len])
            cols = bytes(m[cols_off : cols_off + cols_len])
            (seq2,) = _SEQ.unpack_from(m, _SEQ_OFF)
            if seq1 != seq2:
                continue  # torn read: retry
            try:
                return SegmentFrame(
                    generation=int(generation),
                    fencing=int(fencing),
                    record_line=record.decode("utf-8"),
                    columns=_unpack_columns(cols),
                )
            except (ValueError, UnicodeDecodeError) as exc:
                raise SegmentCorrupt(
                    f"segment at {self.path} payload failed to parse"
                ) from exc
        raise SegmentCorrupt(f"segment at {self.path} seqlock never stabilized")

    def close(self) -> None:
        try:
            self._map.close()
        finally:
            self._file.close()


class SegmentBusPublisher(BusPublisher):
    """BusPublisher that ALSO mirrors every accepted generation into the
    shared-memory segment — one codec (the bus record line is reused
    verbatim), two transports. The bus backlog stays authoritative:
    segment publish failures (overflow, closed map) are absorbed and
    counted, because the NDJSON fallback ladder already covers them."""

    def __init__(self, segment: SnapshotSegment, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.segment = segment
        self.segment_publishes = 0
        self.segment_failures = 0

    def publish(
        self,
        snap: Any,
        *,
        generation: int,
        metrics: Any = None,
        forecast: Any = None,
    ) -> bool:
        accepted = super().publish(
            snap, generation=generation, metrics=metrics, forecast=forecast
        )
        if not accepted:
            return False
        with self._lock:
            line = self._backlog[-1][1]
        try:
            from ..analytics.encode import encode_fleet

            columns = {
                name: encode_fleet(state.view.nodes, state.view.pods)
                for name, state in (getattr(snap, "providers", {}) or {}).items()
            }
            if self.segment.publish(
                line, columns, generation=generation, fencing=self.fencing
            ):
                self.segment_publishes += 1
            else:
                self.segment_failures += 1
        except Exception:  # noqa: BLE001 — the segment is an optimization; the bus is truth
            self.segment_failures += 1
        return True

    def snapshot(self) -> dict[str, Any]:
        out = super().snapshot()
        out["segment_publishes"] = self.segment_publishes
        out["segment_failures"] = self.segment_failures
        out["segment_path"] = self.segment.path
        return out


__all__ = [
    "DEFAULT_SEGMENT_SIZE",
    "HEADER_SIZE",
    "SEGMENT_MAGIC",
    "SEGMENT_VERSION",
    "SegmentBusPublisher",
    "SegmentCorrupt",
    "SegmentError",
    "SegmentFrame",
    "SegmentReader",
    "SegmentUnavailable",
    "SegmentVersionGated",
    "SnapshotSegment",
    "default_segment_path",
]
