"""Pod detail-page injection.

Rebuild of `/root/reference/src/components/PodDetailSection.tsx`: pure
props — takes only the pod being viewed, no context (`:25` header
comment notes it deliberately avoids the provider). Returns None for
pods that request no TPU (`:31`); otherwise rows per container with the
TPU request/limit, plus phase/node/chip-count summary (`:57-111`).
"""

from __future__ import annotations

from typing import Any

from ..domain import objects as obj
from ..domain import tpu
from ..domain.constants import TPU_RESOURCE
from ..ui import NameValueTable, SectionBox
from ..ui.vdom import Element
from .common import unwrap_json_data
from ..pages.common import phase_label


def pod_detail_section(pod: Any) -> Element | None:
    pod = unwrap_json_data(pod)
    if not tpu.is_tpu_requesting_pod(pod):
        return None

    container_rows: list[tuple[str, Any]] = []
    tpu_containers = 0
    for c in obj.pod_containers(pod):
        req = obj.parse_int(obj.container_requests(c).get(TPU_RESOURCE))
        lim = obj.parse_int(obj.container_limits(c).get(TPU_RESOURCE))
        if req or lim:
            tpu_containers += 1
            container_rows.append(
                (
                    f"{c.get('name', '?')} → google.com/tpu",
                    f"request {req} / limit {lim}",
                )
            )

    return SectionBox(
        "TPU",
        NameValueTable(
            [
                ("Phase", phase_label(pod)),
                ("Node", obj.pod_node_name(pod) or "—"),
                ("TPU containers", tpu_containers),
                (
                    "Effective chips",
                    tpu.format_chip_count(tpu.get_pod_chip_request(pod)),
                ),
                *container_rows,
            ]
        ),
        class_="hl-pod-detail",
    )
