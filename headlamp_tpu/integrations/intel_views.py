"""Intel GPU native-view integrations — the reference's own injections
(`/root/reference/src/components/NodeDetailSection.tsx`,
`PodDetailSection.tsx`, `integrations/NodeColumns.tsx`), hosted beside
the TPU ones. Same null-render contracts; a host registers both
providers' sections and each guards itself.
"""

from __future__ import annotations

from typing import Any

from ..context.accelerator_context import ClusterSnapshot
from ..domain import intel
from ..domain import objects as obj
from ..pages.common import phase_label
from ..ui import NameValueTable, SectionBox, UtilizationBar, h
from ..ui.vdom import Element
from .common import unwrap_json_data


def intel_node_detail_section(
    node: Any, snap: ClusterSnapshot | None = None
) -> Element | None:
    """(`NodeDetailSection.tsx`: non-GPU null `:44`, no-capacity null
    `:64-66`, utilization `:69-123`, pods list `:125-133`.)"""
    node = unwrap_json_data(node)
    if not intel.is_intel_gpu_node(node):
        return None
    capacity = intel.get_node_gpu_count(node)
    allocatable = intel.get_node_gpu_allocatable(node)
    if capacity == 0 and allocatable == 0:
        return None

    node_name = obj.name(node)
    rows: list[tuple[str, Any]] = [
        ("Type", intel.format_gpu_type(intel.get_node_gpu_type(node))),
        ("Devices (capacity)", capacity),
        ("Devices (allocatable)", allocatable),
    ]
    pod_list: Any
    if snap is not None and not snap.loading:
        state = snap.provider("intel")
        node_pods = [p for p in state.pods if obj.pod_node_name(p) == node_name]
        in_use = sum(
            intel.get_pod_device_request(p)
            for p in node_pods
            if obj.pod_phase(p) == "Running"
        )
        rows.append(("In use", UtilizationBar(in_use, allocatable, unit="GPUs")))
        pod_list = h(
            "ul",
            {"class_": "hl-node-pods"},
            [
                h(
                    "li",
                    None,
                    f"{obj.namespace(p)}/{obj.name(p)} "
                    f"({intel.get_pod_device_request(p)} GPUs)",
                )
                for p in node_pods
            ]
            or [h("li", None, "No GPU pods on this node")],
        )
    else:
        pod_list = h("p", {"class_": "hl-loading-inline"}, "Loading…")

    return SectionBox(
        "Intel GPU", NameValueTable(rows), pod_list, class_="hl-node-detail"
    )


def intel_pod_detail_section(pod: Any) -> Element | None:
    """(`PodDetailSection.tsx`: pure props `:25`, non-GPU null `:31`,
    per container×resource rows `:57-83`, summary `:93-111`.)"""
    pod = unwrap_json_data(pod)
    if not intel.is_gpu_requesting_pod(pod):
        return None

    rows: list[tuple[str, Any]] = [
        ("Phase", phase_label(pod)),
        ("Node", obj.pod_node_name(pod) or "—"),
    ]
    gpu_containers = 0
    for c in obj.pod_containers(pod):
        resources = intel.get_container_gpu_resources(c)
        if resources:
            gpu_containers += 1
        for resource, (req, lim) in resources.items():
            rows.append(
                (
                    f"{c.get('name', '?')} → {intel.format_gpu_resource_name(resource)}",
                    f"request {req} / limit {lim}",
                )
            )
    rows.insert(2, ("GPU containers", gpu_containers))

    return SectionBox("Intel GPU", NameValueTable(rows), class_="hl-pod-detail")


def build_node_intel_columns() -> list[dict[str, Any]]:
    """(`NodeColumns.tsx:17-48`: 'GPU Type' and 'GPU Devices' with
    '—' fallback.)"""

    def type_cell(node: Any) -> str:
        node = unwrap_json_data(node)
        if not intel.is_intel_gpu_node(node):
            return "—"
        return intel.format_gpu_type(intel.get_node_gpu_type(node))

    def devices_cell(node: Any) -> str:
        node = unwrap_json_data(node)
        if not intel.is_intel_gpu_node(node):
            return "—"
        return str(intel.get_node_gpu_count(node))

    return [
        {"label": "GPU Type", "getter": type_cell},
        {"label": "GPU Devices", "getter": devices_cell},
    ]
