"""Native-view integrations.

The reference injects accelerator context into Headlamp's own Node/Pod
detail pages and Nodes table
(`/root/reference/src/components/NodeDetailSection.tsx`,
`PodDetailSection.tsx`, `integrations/NodeColumns.tsx`). These are the
same injections for TPU: a section for a single Node, a section for a
single Pod, and extra Nodes-table columns — each guarded to render
nothing for non-TPU resources.
"""

from .node_detail import node_detail_section
from .pod_detail import pod_detail_section
from .node_columns import build_node_tpu_columns
from .intel_views import (
    build_node_intel_columns,
    intel_node_detail_section,
    intel_pod_detail_section,
)

__all__ = [
    "node_detail_section",
    "pod_detail_section",
    "build_node_tpu_columns",
    "build_node_intel_columns",
    "intel_node_detail_section",
    "intel_pod_detail_section",
]
