"""Nodes-table column integration.

Rebuild of `/root/reference/src/components/integrations/NodeColumns.tsx`:
column definitions appended to the native Nodes table, each getter
guarded so non-TPU rows show '—' (`:17-48`). Consumed by the
registration layer's columns processor.
"""

from __future__ import annotations

from typing import Any

from ..domain import tpu
from .common import unwrap_json_data


def _gen_cell(node: Any) -> str:
    node = unwrap_json_data(node)
    if not tpu.is_tpu_node(node):
        return "—"
    return tpu.format_accelerator(tpu.get_node_accelerator(node))


def _chips_cell(node: Any) -> str:
    node = unwrap_json_data(node)
    if not tpu.is_tpu_node(node):
        return "—"
    return str(tpu.get_node_chip_capacity(node))


def _topology_cell(node: Any) -> str:
    node = unwrap_json_data(node)
    if not tpu.is_tpu_node(node):
        return "—"
    return tpu.get_node_topology(node) or "—"


def build_node_tpu_columns() -> list[dict[str, Any]]:
    """Column defs: label + getter, the SimpleTable/processor contract
    (`NodeColumns.tsx:17` returns the same shape for the Headlamp
    table)."""
    return [
        {"label": "TPU Type", "getter": _gen_cell},
        {"label": "TPU Chips", "getter": _chips_cell},
        {"label": "TPU Topology", "getter": _topology_cell},
    ]
