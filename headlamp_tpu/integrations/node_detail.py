"""Node detail-page injection.

Rebuild of `/root/reference/src/components/NodeDetailSection.tsx`:
renders *inside the native Node page*, so it takes the single node
being viewed plus the shared snapshot for pods-on-node context. Returns
None (renders nothing) for non-TPU nodes (`:44,64-66`) — the injection
must be invisible on a CPU node's page.
"""

from __future__ import annotations

from typing import Any

from ..context.accelerator_context import ClusterSnapshot
from ..domain import objects as obj
from ..domain import tpu
from ..topology.slices import group_slices
from ..ui import NameValueTable, SectionBox, StatusLabel, UtilizationBar, h
from ..ui.vdom import Element
from .common import unwrap_json_data


def node_detail_section(node: Any, snap: ClusterSnapshot | None = None) -> Element | None:
    node = unwrap_json_data(node)
    if not tpu.is_tpu_node(node):
        return None
    capacity = tpu.get_node_chip_capacity(node)
    allocatable = tpu.get_node_chip_allocatable(node)
    if capacity == 0 and allocatable == 0:
        # Labeled but no TPU resource registered (`:64-66` shows nothing
        # when no gpu capacity/allocatable keys exist).
        return None

    node_name = obj.name(node)
    rows: list[tuple[str, Any]] = [
        ("Generation", tpu.format_accelerator(tpu.get_node_accelerator(node))),
        ("Topology", tpu.get_node_topology(node) or "—"),
        ("Chips (capacity)", capacity),
        ("Chips (allocatable)", allocatable),
    ]

    pod_list = None
    if snap is not None and not snap.loading:
        state = snap.provider("tpu")
        node_pods = [
            p for p in state.pods if obj.pod_node_name(p) == node_name
        ]
        in_use = sum(
            tpu.get_pod_chip_request(p)
            for p in node_pods
            if obj.pod_phase(p) == "Running"
        )
        rows.append(("Chips in use", UtilizationBar(in_use, allocatable, unit="chips")))
        # Slice membership — which slice this host belongs to and its
        # worker index (no Intel analogue; slice context is the most
        # useful fact on a TPU node's page).
        for sl in group_slices(state.nodes):
            for w in sl.workers:
                if w.node_name == node_name:
                    rows.append(("Slice", sl.slice_id))
                    rows.append(("Worker index", w.worker_id))
                    rows.append(("Slice health", StatusLabel(sl.health, sl.health)))
                    break
        pod_list = h(
            "ul",
            {"class_": "hl-node-pods"},
            [
                h(
                    "li",
                    None,
                    f"{obj.namespace(p)}/{obj.name(p)} "
                    f"({tpu.format_chip_count(tpu.get_pod_chip_request(p))})",
                )
                for p in node_pods
            ]
            or [h("li", None, "No TPU pods on this node")],
        )
    else:
        # Context not hydrated: show node-local facts with a loading
        # hint for the rest (`:125-133`'s 'Loading…' state).
        pod_list = h("p", {"class_": "hl-loading-inline"}, "Loading…")

    return SectionBox("TPU", NameValueTable(rows), pod_list, class_="hl-node-detail")
