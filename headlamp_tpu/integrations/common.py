"""Shared integration helpers."""

from __future__ import annotations

from typing import Any, Mapping


def unwrap_json_data(resource: Any) -> Any:
    """Headlamp hands detail-view callbacks either a raw object or a
    wrapper with the raw object under ``jsonData``
    (`/root/reference/src/components/NodeDetailSection.tsx:40-41` and
    `NodeColumns.tsx:21-25` both unwrap defensively). Accept both."""
    if isinstance(resource, Mapping) and isinstance(resource.get("jsonData"), Mapping):
        return resource["jsonData"]
    return resource
