"""Pallas TPU kernel: fused forecaster inference.

The forecaster's forward pass is three dense layers
(`forecast.forward`). Under plain XLA each layer's activation can round
-trip through HBM between fused regions; at serving scale (thousands of
chips × frequent refresh) the op is HBM-bandwidth-bound, which makes it
this framework's honest Pallas target (per
`/opt/skills/guides/pallas_guide.md`): all three weights fit comfortably
in VMEM (~90 KB), so one kernel keeps every intermediate on-chip and
touches HBM exactly twice per row (read x, write y).

Layout notes (guide §Tiling):
- Batch is tiled in blocks of 128 rows (grid dim 0); window (32) and
  horizon (8) are zero-padded to the 128-lane width — padded columns
  multiply zero-padded weight rows, contributing nothing.
- Matmuls run through the MXU in bf16 with f32 accumulation
  (``preferred_element_type``), matching the XLA reference path's
  precision recipe exactly so parity tests can use tight tolerances.

The kernel is inference-only (no custom VJP) — training goes through
the XLA path, which autodiff already handles.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .forecast import ForecastConfig, Params

#: Batch rows per grid step (f32 min sublane tile is 8; 128 keeps the
#: MXU fed).
_BLOCK_B = 128
#: Lane width everything pads to.
_LANES = 128


def _forward_kernel(
    x_ref: Any,
    w1_ref: Any,
    b1_ref: Any,
    w2_ref: Any,
    b2_ref: Any,
    w3_ref: Any,
    b3_ref: Any,
    out_ref: Any,
) -> None:
    """One batch tile: y = sigmoid(gelu(gelu(x@w1+b1)@w2+b2)@w3+b3),
    entirely in VMEM."""

    def dense(h: jax.Array, w_ref: Any, b_ref: Any) -> jax.Array:
        y = jax.lax.dot_general(
            h.astype(jnp.bfloat16),
            w_ref[:].astype(jnp.bfloat16),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # Biases arrive padded to a full (8, 128) f32 tile; row 0 is
        # the real bias, broadcast over the batch tile.
        return y + b_ref[0:1, :]

    h = jax.nn.gelu(dense(x_ref[:], w1_ref, b1_ref))
    h = jax.nn.gelu(dense(h, w2_ref, b2_ref))
    out_ref[:] = jax.nn.sigmoid(dense(h, w3_ref, b3_ref))


def _pad2(a: jax.Array, rows: int, cols: int) -> jax.Array:
    return jnp.pad(a, ((0, rows - a.shape[0]), (0, cols - a.shape[1])))


def forecast_forward_padded(
    params: Params, x: jax.Array, *, batch_p: int, horizon: int, interpret: bool
) -> jax.Array:
    """Trace-time body: padding → kernel → un-pad. Call it inside an
    enclosing jit — the fused fit+infer program does — or through the
    jitted :func:`_pallas_program` wrapper for standalone inference."""
    x_p = _pad2(x.astype(jnp.float32), batch_p, _LANES)
    w1_p = _pad2(params["w1"].astype(jnp.float32), _LANES, _LANES)
    w2_p = _pad2(params["w2"].astype(jnp.float32), _LANES, _LANES)
    w3_p = _pad2(params["w3"].astype(jnp.float32), _LANES, _LANES)
    b1_p = _pad2(params["b1"].reshape(1, -1).astype(jnp.float32), 8, _LANES)
    b2_p = _pad2(params["b2"].reshape(1, -1).astype(jnp.float32), 8, _LANES)
    b3_p = _pad2(params["b3"].reshape(1, -1).astype(jnp.float32), 8, _LANES)
    out = _padded_forward(
        x_p, w1_p, b1_p, w2_p, b2_p, w3_p, b3_p, interpret=interpret
    )
    return out[: x.shape[0], :horizon]


@functools.partial(jax.jit, static_argnames=("batch_p", "horizon", "interpret"))
def _pallas_program(
    params: Params, x: jax.Array, *, batch_p: int, horizon: int, interpret: bool
) -> jax.Array:
    """Padding → kernel → un-pad as ONE jitted program: each un-jitted
    jnp.pad is its own device dispatch, and over a tunneled/remote TPU
    those seven round-trips cost more than the kernel itself."""
    return forecast_forward_padded(
        params, x, batch_p=batch_p, horizon=horizon, interpret=interpret
    )


def pallas_batch_p(batch: int) -> int:
    """Padded batch rows for the kernel grid (multiple of _BLOCK_B)."""
    return max(_BLOCK_B, -(-batch // _BLOCK_B) * _BLOCK_B)


def check_single_tile(window: int, hidden: int, horizon: int) -> None:
    """Raise unless every dimension fits the single-tile kernel width —
    shared guard for the standalone and fused callers."""
    if hidden > _LANES or window > _LANES or horizon > _LANES:
        raise ValueError(
            f"window={window}, hidden={hidden}, horizon={horizon}: every "
            f"dimension must fit the single-tile kernel width {_LANES}"
        )


def _padded_forward(
    x_p: jax.Array,
    w1_p: jax.Array,
    b1_p: jax.Array,
    w2_p: jax.Array,
    b2_p: jax.Array,
    w3_p: jax.Array,
    b3_p: jax.Array,
    *,
    interpret: bool,
) -> jax.Array:
    n_blocks = x_p.shape[0] // _BLOCK_B
    weight_spec = pl.BlockSpec(
        (_LANES, _LANES), lambda i: (0, 0), memory_space=pltpu.VMEM
    )
    bias_spec = pl.BlockSpec((8, _LANES), lambda i: (0, 0), memory_space=pltpu.VMEM)
    grid_spec = pl.GridSpec(
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec(
                (_BLOCK_B, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
            ),
            weight_spec,
            bias_spec,
            weight_spec,
            bias_spec,
            weight_spec,
            bias_spec,
        ],
        out_specs=pl.BlockSpec(
            (_BLOCK_B, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
    )
    return pl.pallas_call(
        _forward_kernel,
        out_shape=jax.ShapeDtypeStruct((x_p.shape[0], _LANES), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(x_p, w1_p, b1_p, w2_p, b2_p, w3_p, b3_p)


def forecast_forward_pallas(
    params: Params,
    x: jax.Array,
    cfg: ForecastConfig | None = None,
    *,
    interpret: bool | None = None,
) -> jax.Array:
    """Drop-in replacement for ``forecast.forward`` on the inference
    path: [batch, window] -> [batch, horizon]. ``interpret`` defaults to
    True off-TPU (the guide's debugging mode) and False on TPU."""
    cfg = cfg or ForecastConfig()
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"

    batch = x.shape[0]
    window = x.shape[1]
    hidden = params["w1"].shape[1]
    horizon = params["w3"].shape[1]
    check_single_tile(window, hidden, horizon)
    return _pallas_program(
        params,
        x,
        batch_p=pallas_batch_p(batch),
        horizon=horizon,
        interpret=bool(interpret),
    )
