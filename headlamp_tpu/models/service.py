"""Forecast service: UtilizationHistory → page-ready forecast view.

The glue between the metrics client's range-query output and the
MetricsPage: fits the forecaster on the fetched traces and summarizes
per-chip risk. Pages stay pure — they render a ForecastView; this
module owns the jax calls.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

from ..metrics.client import UtilizationHistory
from ..obs.jaxcost import track as _jax_track
from ..obs.trace import span as _span
from .forecast import (
    COLD_MSE_TOLERANCE,
    WARM_STEPS,
    _DEMOTION_MSE_FLOOR,
    ForecastConfig,
    InferenceDispatch,
    WarmState,
    fit_and_forecast_incremental,
    fit_and_forecast_with_dispatch,
)


@dataclass
class ChipForecast:
    node: str
    accelerator_id: str
    current: float
    predicted_peak: float
    predicted_mean: float
    #: True when the chip is predicted to cross the 90% saturation line
    #: within the horizon.
    saturation_risk: bool


@dataclass
class ForecastView:
    horizon_s: int
    window_s: int
    chips: list[ChipForecast] = field(default_factory=list)
    fit_ms: float = 0.0
    #: Inference path that actually served the prediction ("pallas" on a
    #: TPU backend unless the kernel failed, else "xla") — surfaced on
    #: the metrics page so a silently-broken kernel is visible.
    inference_path: str = "xla"
    #: Why Pallas fell back to XLA, when it was tried and failed.
    inference_fallback_reason: str | None = None
    #: Final training MSE of the online fit (None on the persistence
    #: path) — the model's self-assessment, shown so operators can judge
    #: how much to trust the prediction.
    fit_mse: float | None = None
    #: Generation of the warm-start carry this fit refined (ADR-015);
    #: None when the fit was from-scratch cold with no carry consulted.
    carried_from_generation: int | None = None
    #: Why a warm refinement self-demoted to a cold refit — never-silent
    #: demotion record, mirrored from the InferenceDispatch.
    warm_demotion_reason: str | None = None
    #: What the fit trained on, mirrored from the dispatch (ADR-018):
    #: "history" once the in-process tier holds a full training window,
    #: else "live-window" — the metrics page and /sloz surface it so
    #: every forecast is auditable back to its data.
    data_source: str = "live-window"

    @property
    def at_risk(self) -> list[ChipForecast]:
        return [c for c in self.chips if c.saturation_risk]


#: Saturation line shared with the UI kit's critical threshold.
SATURATION_PCT = 90.0


def compute_forecast(
    transport: Any, metrics: Any, *, clock: Callable[[], float] | None = None
) -> ForecastView | None:
    """Shared metrics-route glue for every host (HTTP server, CLI):
    fetch history for the snapshot's Prometheus and fit, degrading to
    None on missing extras, unusable jax backends, or thin history —
    one definition so consumers cannot drift on what the metrics page
    shows."""
    import time as _time

    from ..metrics.client import fetch_utilization_history

    if metrics is None or not metrics.chips:
        return None
    try:
        # ADR-013: the two forecast phases traced separately — the
        # range query is network-bound, the fit is device-bound, and a
        # slow metrics page needs to show WHICH one it paid.
        with _span("forecast.history"):
            history = fetch_utilization_history(
                transport,
                prometheus=(metrics.namespace, metrics.service),
                clock=clock or _time.time,
                preferred_query=metrics.resolved_series.get("tensorcore_utilization"),
            )
        if history is None:
            return None
        return forecast_from_history(history)
    except Exception:
        # Forecast is a progressive enhancement — any failure costs the
        # section, never the page.
        return None


def forecast_from_history(
    history: UtilizationHistory,
    cfg: ForecastConfig | None = None,
    *,
    steps: int = 60,
) -> ForecastView:
    """Fit + predict + summarize. Deterministic (fixed seed)."""
    import time

    import numpy as np

    cfg = cfg or ForecastConfig()
    t0 = time.perf_counter()
    with _span(
        "forecast.fit", series=len(history.series), steps=steps
    ) as fit_span:
        preds, dispatch = fit_and_forecast_with_dispatch(
            np.asarray(history.series), cfg, steps=steps
        )
        if fit_span is not None:
            fit_span.attrs["inference_path"] = dispatch.path
        if dispatch.fit_mse is not None:
            # One device_get for predictions AND the fit-quality scalar —
            # a separate float() would cost an extra tunnel round-trip. Via
            # the transfer funnel it also coalesces with the fleet rollup's
            # fetch when a request batch is active.
            from ..runtime import transfer

            preds, fit_mse_arr = transfer.fetch((preds, dispatch.fit_mse))
            fit_mse = float(fit_mse_arr)
        else:
            preds = np.asarray(preds)
            fit_mse = None
    fit_ms = round((time.perf_counter() - t0) * 1000, 1)
    return _summarize(history, cfg, preds, dispatch, fit_ms, fit_mse)


def _summarize(
    history: UtilizationHistory,
    cfg: ForecastConfig,
    preds: Any,
    dispatch: InferenceDispatch,
    fit_ms: float,
    fit_mse: float | None,
) -> ForecastView:
    """Host-side per-chip risk summary shared by the cold and warm
    entries — one definition so they cannot drift on what "at risk"
    means."""
    chips = []
    for key, trace, pred in zip(history.keys, history.series, preds):
        peak = float(pred.max())
        chips.append(
            ChipForecast(
                node=key[0],
                accelerator_id=key[1],
                current=float(trace[-1]),
                predicted_peak=peak,
                predicted_mean=float(pred.mean()),
                saturation_risk=peak * 100 >= SATURATION_PCT,
            )
        )
    chips.sort(key=lambda c: -c.predicted_peak)
    n_samples = len(history.series[0]) if history.series else 0
    return ForecastView(
        horizon_s=cfg.horizon * history.step_s,
        # The fit consumes the WHOLE fetched trace (sliding windows over
        # all of it), so report that — not cfg.window — as the history
        # span shown to operators.
        window_s=max(0, (n_samples - 1)) * history.step_s,
        chips=chips,
        fit_ms=fit_ms,
        inference_path=dispatch.path,
        inference_fallback_reason=dispatch.fallback_reason,
        fit_mse=fit_mse,
        carried_from_generation=dispatch.carried_from_generation,
        warm_demotion_reason=dispatch.warm_demotion_reason,
        data_source=dispatch.data_source,
    )


def forecast_from_history_incremental(
    history: UtilizationHistory,
    cfg: ForecastConfig | None = None,
    *,
    state: WarmState | None = None,
    steps: int = 60,
    warm_steps: int = WARM_STEPS,
    data_source: str = "live-window",
) -> tuple[ForecastView, WarmState | None]:
    """Warm-start variant of :func:`forecast_from_history`: refines the
    carried :class:`WarmState` (ADR-015) and returns the new carry with
    the view. The incremental entry already materializes predictions +
    MSE in one host fetch, so no transfer-funnel round-trip here.
    ``data_source`` names what ``history`` is (ADR-018: "history" for
    the captured tier, "live-window" for a fresh range query) and is
    stamped into the dispatch record the view mirrors."""
    import time

    import numpy as np

    cfg = cfg or ForecastConfig()
    t0 = time.perf_counter()
    with _span(
        "forecast.fit", series=len(history.series), steps=steps, warm=state is not None
    ) as fit_span:
        preds, dispatch, new_state = fit_and_forecast_incremental(
            np.asarray(history.series), cfg,
            state=state, steps=steps, warm_steps=warm_steps,
        )
        dispatch = dispatch._replace(data_source=data_source)
        if fit_span is not None:
            fit_span.attrs["inference_path"] = dispatch.path
            fit_span.attrs["data_source"] = data_source
    fit_ms = round((time.perf_counter() - t0) * 1000, 1)
    fit_mse = None if dispatch.fit_mse is None else float(dispatch.fit_mse)
    view = _summarize(history, cfg, np.asarray(preds), dispatch, fit_ms, fit_mse)
    return view, new_state


def forecast_slo_burn(
    series: list[float],
    *,
    state: WarmState | None = None,
    steps: int = 60,
) -> tuple[list[float] | None, WarmState | None]:
    """Fit the service's OWN scrape→paint latency series and return
    predicted latencies for the next ``steps`` ticks, plus the warm
    carry (ADR-015) — the SLO engine's self-forecast (ADR-016)
    classifies them against the objective threshold to project budget
    exhaustion. Lives here, not in obs/, because the inline-fit gate
    confines ``fit_and_forecast*`` to the models layer; degrades to
    ``(None, state)`` on any failure (jax-less host, thin series) so
    /sloz renders a named reason instead of 500ing."""
    import numpy as np

    if len(series) < 2:
        return None, state
    try:
        with _span("slo.budget_fit", series=len(series), steps=steps):
            # ADR-019 cost ledger: the burn self-forecast is its own
            # program row (the incremental entry also records the
            # underlying fused program — nested tracks are additive).
            with _jax_track(
                "slo.burn_forecast", (len(series), steps, state is not None)
            ):
                # The fused entry is (n_chips, length); the latency
                # series is one "chip". (Pre-ADR-019 this passed the
                # bare 1-D array, so the shape unpack raised and every
                # self-forecast silently degraded to None — the cost
                # ledger made the missing program row visible.)
                preds, _dispatch, new_state = fit_and_forecast_incremental(
                    np.asarray(series, dtype=float)[None, :],
                    ForecastConfig(),
                    state=state,
                    steps=steps,
                )
        return [float(p) for p in np.asarray(preds).ravel()], new_state
    except Exception:
        # Same progressive-enhancement posture as the page forecast.
        return None, state


def _fused_rollup_forecast(
    history: UtilizationHistory,
    cfg: ForecastConfig,
    state: WarmState | None,
    fleet_view: Any,
    data_source: str,
) -> tuple[ForecastView, WarmState | None] | None:
    """Serve the fleet rollup AND the warm forecast refinement from the
    single donated ``fused.rollup_and_forecast`` program (ADR-020): the
    ADR-012 device-cached fleet columns feed the rollup stage directly,
    the params/opt_state carry is donated, and ONE coalesced
    device_get materializes (rollup, predictions, mse). The finalized
    rollup dict is parked in :data:`~headlamp_tpu.runtime.device_cache.
    rollup_results` so the overview's ``fleet_stats`` for the same
    snapshot version does zero device work.

    Returns ``(view, new_state)``, or ``None`` whenever the fused path
    can't serve — no warm carry, carry/cfg mismatch, unversioned or
    small fleet, registry cold, or no precompiled bucket — and the
    caller runs the classic split path unchanged. A novel at-scale
    fleet shape schedules a background backfill compile so the NEXT
    request hits."""
    import time

    import jax
    import numpy as np

    from ..analytics.stats import XLA_ROLLUP_MIN_NODES
    from ..runtime import transfer
    from ..runtime.device_cache import fleet_cache, rollup_results
    from . import aot
    from .forecast import _platform_and_pallas, pad_series_to_bucket

    reg = aot.registry()
    if reg is None or not reg.ready():
        return None
    if fleet_view is None or getattr(fleet_view, "version", None) is None:
        return None
    if getattr(fleet_view.provider, "name", None) != "tpu":
        return None
    if len(fleet_view.nodes) < XLA_ROLLUP_MIN_NODES:
        # Below the crossover the Python rollup wins anyway — fusing
        # would force device work the measured policy avoids.
        return None
    series = np.asarray(history.series, dtype=np.float32)
    n_chips, length = series.shape
    if length < cfg.window + cfg.horizon:
        return None
    if state is None or state.cfg != cfg or state.n_chips != n_chips:
        return None
    bucket = aot.chip_bucket_for(n_chips)
    if bucket is None:
        reg.note_bucket_miss("fused.rollup_and_forecast")
        return None
    inference, batch_p, fallback = _platform_and_pallas(cfg, n_chips)
    try:
        fleet = fleet_cache.fleet_for(fleet_view)
    except Exception:  # noqa: BLE001 — broken backend → classic path
        return None
    ledger_key = (
        tuple(fleet.node_capacity.shape),
        tuple(fleet.pod_request.shape),
        bucket, length, cfg, WARM_STEPS, inference, batch_p,
    )
    exe = reg.executable("fused.rollup_and_forecast", ledger_key)
    if exe is None:
        # Novel at-scale shape: compile it in the background so the
        # next request at this fleet size hits.
        reg.ensure("fused.rollup_and_forecast", ledger_key)
        return None

    t0 = time.perf_counter()
    import jax.numpy as jnp

    padded, weights = pad_series_to_bucket(jnp.asarray(series), bucket)
    # Only the (params, opt_state) carry is donated — the padded series
    # has no output to alias and the fleet columns are shared (ADR-020).
    donated = sum(
        int(leaf.nbytes)
        for leaf in jax.tree_util.tree_leaves((state.params, state.opt_state))
    )
    try:
        with _span(
            "forecast.fused", nodes=len(fleet_view.nodes), chips=n_chips
        ):
            with _jax_track("fused.rollup_and_forecast", ledger_key):
                rollup_dev, out, params, opt_state, mse_dev = exe(
                    fleet.node_capacity, fleet.node_allocatable,
                    fleet.node_ready, fleet.node_generation,
                    fleet.node_valid, fleet.pod_request, fleet.pod_phase,
                    fleet.pod_node_idx, fleet.pod_valid,
                    padded, weights, state.params, state.opt_state,
                )
            # ONE coalesced round-trip for all three stages' outputs
            # (ADR-012 funnel discipline).
            rollup_host, preds, warm_mse = transfer.fetch(
                (rollup_dev, out[:n_chips], mse_dev)
            )
    except Exception as exc:  # noqa: BLE001 — AOT is an optimization
        # NOTE: the donated carry may already be consumed; the classic
        # fallback's warm attempt will then demote to a cold refit —
        # degraded, never wrong.
        reg.note_exec_failure(
            "fused.rollup_and_forecast", f"{type(exc).__name__}: {exc}"[:200]
        )
        return None
    reg.note_donation(donated)

    from ..analytics.fleet_jax import rollup_host_view

    rollup_results.store(
        fleet_view.provider.name,
        fleet_view.version,
        rollup_host_view(rollup_host, fleet.n_nodes),
    )

    warm_mse = float(warm_mse)
    bound = COLD_MSE_TOLERANCE * max(state.cold_mse, _DEMOTION_MSE_FLOOR)
    if warm_mse > bound:
        # Same never-silent demotion contract as the classic warm path:
        # the refinement is thrown away, a cold refit runs (the rollup
        # half above is untouched — it never depended on the carry),
        # and the lineage is stitched so the dispatch record still says
        # which generation was consulted and why it was rejected.
        reason = (
            f"warm mse {warm_mse:.3g} > {COLD_MSE_TOLERANCE:g}x "
            f"cold {state.cold_mse:.3g}"
        )
        view, new_state = forecast_from_history_incremental(
            history, cfg, state=None, data_source=data_source
        )
        if new_state is not None:
            new_state = new_state._replace(generation=state.generation + 1)
        view.carried_from_generation = state.generation
        view.warm_demotion_reason = reason
        return view, new_state

    new_state = WarmState(
        params, opt_state, state.cold_mse, state.generation, cfg, n_chips
    )
    dispatch = InferenceDispatch(
        f"{inference}-warm", fallback, fit_mse=warm_mse,
        carried_from_generation=state.generation,
        data_source=data_source,
    )
    fit_ms = round((time.perf_counter() - t0) * 1000, 1)
    view = _summarize(
        history, cfg, np.asarray(preds), dispatch, fit_ms, warm_mse
    )
    return view, new_state


def compute_forecast_incremental(
    transport: Any,
    metrics: Any,
    *,
    state: WarmState | None = None,
    clock: Callable[[], float] | None = None,
    history_store: Any = None,
    fleet_view: Any = None,
) -> tuple[ForecastView | None, WarmState | None]:
    """:func:`compute_forecast` with the ADR-015 warm-start carry:
    returns ``(view, new_state)``; any failure degrades to ``(None,
    state)`` — the carry survives a flaky scrape so the next attempt
    can still warm-start.

    With a ``history_store`` (ADR-018), the captured in-process tier is
    consulted FIRST: once it holds at least one full training window of
    aligned per-chip scrapes, the fit trains on real history — no range
    query at all — and the view's ``data_source`` says so. A thin or
    absent store falls through to the live range query unchanged."""
    import time as _time

    from ..metrics.client import fetch_utilization_history

    if metrics is None or not metrics.chips:
        return None, state
    try:
        cfg = ForecastConfig()
        if history_store is not None:
            captured = history_store.utilization_history(
                clock=clock or _time.time,
                # length >= window + horizon is the fit's hard floor
                # (below it the incremental entry serves persistence);
                # requiring it here keeps "history" meaning "really
                # trained on history".
                min_points=cfg.window + cfg.horizon,
            )
            if captured is not None:
                fused = _fused_rollup_forecast(
                    captured, cfg, state, fleet_view, "history"
                )
                if fused is not None:
                    return fused
                return forecast_from_history_incremental(
                    captured, cfg, state=state, data_source="history"
                )
        with _span("forecast.history"):
            history = fetch_utilization_history(
                transport,
                prometheus=(metrics.namespace, metrics.service),
                clock=clock or _time.time,
                preferred_query=metrics.resolved_series.get("tensorcore_utilization"),
            )
        if history is None:
            return None, state
        fused = _fused_rollup_forecast(
            history, cfg, state, fleet_view, "live-window"
        )
        if fused is not None:
            return fused
        return forecast_from_history_incremental(history, state=state)
    except Exception:
        # Forecast is a progressive enhancement — any failure costs the
        # section, never the page.
        return None, state
