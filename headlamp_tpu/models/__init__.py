"""Models — TPU-native telemetry forecasting.

The framework's flagship numeric model: an MLP forecaster over per-chip
utilization windows (predicting near-future TensorCore load so the
dashboard can warn before saturation). Pure-functional params, optax
training, bfloat16 matmuls sized for the MXU, and dp×tp mesh shardings
in ``parallel.mesh``. No reference analogue (the Intel plugin computes
nothing; SURVEY.md §2.2) — this is the TPU-first capability the
BASELINE metrics page gains on top of parity.
"""

from .forecast import (
    ForecastConfig,
    InferenceDispatch,
    WarmState,
    fit_and_forecast,
    fit_and_forecast_incremental,
    fit_and_forecast_with_dispatch,
    forecast_next,
    forecast_next_with_dispatch,
    forward,
    init_params,
    loss_fn,
    make_train_step,
    make_windows,
    param_shardings,
    synthetic_telemetry,
)

__all__ = [
    "ForecastConfig",
    "InferenceDispatch",
    "WarmState",
    "fit_and_forecast",
    "fit_and_forecast_incremental",
    "fit_and_forecast_with_dispatch",
    "forecast_next",
    "forecast_next_with_dispatch",
    "forward",
    "init_params",
    "loss_fn",
    "make_train_step",
    "make_windows",
    "param_shardings",
    "synthetic_telemetry",
]
