"""AOT program registry — startup absorbs every hot compile (ADR-020).

The ADR-019 cost ledger proved where the first-request latency spike
comes from: each hot jitted program (fleet rollup, cold/warm forecast
fit, the fused rollup+forecast, the sharded mesh rollup) pays
trace+compile on its FIRST call per shape — 0.25–1.1 s each on the CI
host, stacked onto whichever request arrives first. This registry moves
those compiles off the request path: at ``serve()`` startup a daemon
thread lowers and compiles each program at a small set of canonical
bucketed shapes via ``jit(...).lower(...).compile()``, tracking each
one in the cost ledger with ``phase="startup"``. Request-side call
sites look up the compiled executable by the EXACT ``(name, key)`` the
startup thread registered (the same pair they hand the ledger), so a
hit classifies as a warm dispatch and the post-warmup request-compile
count — the acceptance number — stays zero.

Shape policy: arbitrary fleet sizes are padded UP to the next bucket —
chip counts to :data:`CHIP_BUCKETS` (with a per-chip weight vector so
padding never leaks into the fit; see ``forecast.pad_series_to_bucket``)
and rollup columns to the power-of-two node/pod buckets the encoder
already produces (:data:`ROLLUP_BUCKETS` covers the at-scale fixtures;
``ensure_rollup_shapes`` backfills observed shapes in the background).
A shape no bucket covers is a MISS, never an error: the caller runs the
plain jitted path (counted by the ledger as a request-phase compile)
and the miss is visible on ``/healthz`` and ``/metricsz``.

Import-safe on jax-less hosts by design: the server imports this module
unconditionally (serve/healthz wiring), so module scope is stdlib-only
and jax enters lazily inside the compile thread. A host whose jax
import fails parks the registry in the "unavailable" state — lookups
all miss, serving degrades to exactly the pre-registry behavior.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

from ..obs import jaxcost as _jaxcost
from ..obs.metrics import registry as _metrics_registry

#: Chip-axis buckets the forecast programs precompile at. 8 covers the
#: SLO burn self-forecast (1 series) and toy fleets, 64 the demo fleet
#: (16 nodes × 4 chips — also what the bench's Prometheus fixture
#: serves), 256 headroom for larger scrapes. Chip counts above the top
#: bucket fall back to the plain jitted path (counted miss).
CHIP_BUCKETS: tuple[int, ...] = (8, 64, 256)

#: (node_pad, pod_pad) column buckets precompiled for the rollup and
#: the viewport region rollup — the encoder's power-of-two padding for
#: the 256-node bench fleet, the 1024-node large fixture, and the
#: 4k/16k viewport fixtures (ADR-026), i.e. the at-scale shapes (below
#: ``XLA_ROLLUP_MIN_NODES`` Python serves the rollup anyway). The TPU
#: view's pod list pads to the SAME power of two as its node list at
#: every fixture size (measured: 248 nodes/180 pods → (256, 256);
#: 991/704 → (1024, 1024); ``fleet_viewport`` keeps pods ≤ nodes by
#: construction), hence the square pairs. Other observed shapes arrive
#: via :meth:`AotProgramRegistry.ensure_rollup_shapes`.
ROLLUP_BUCKETS: tuple[tuple[int, int], ...] = (
    (256, 256),
    (1024, 1024),
    (4096, 4096),
    (16384, 16384),
)

#: Buckets the FUSED rollup+forecast precompiles at — deliberately only
#: the pre-viewport sizes. The fusion exists for the dashboard
#: forecast path, which the 4k/16k viewport paints never take (they
#: serve windowed rows + region rollups); compiling the fused program
#: at 16384 would roughly double startup compile time for a shape with
#: no caller. A 4k+ fleet that DOES hit the fused path falls back to
#: the split rollup→forecast programs (both AOT-warm).
FUSED_BUCKETS: tuple[tuple[int, int], ...] = ROLLUP_BUCKETS[:2]

#: Fleet sizes ``bench_viewport`` paints (ADR-026). Startup asserts the
#: bucket table covers every one of them — the guard that keeps
#: ``request_compiles()==0`` at 16k from silently regressing if the
#: bucket table shrinks.
VIEWPORT_FLEET_SIZES: tuple[int, ...] = (1024, 4096, 16384)

#: History length of the live-window range query (window_s=3600,
#: step_s=60 → 61 samples) — THE page-forecast series length.
LIVE_WINDOW_SAMPLES = 61

#: Steady-state length of the SLO burn self-forecast series (the paint
#: ring's maxlen). While the ring is still filling, lengths 48..511 are
#: bucket misses on purpose — padding the TIME axis would train on
#: fabricated samples.
SLO_SERIES_STEADY = 512


def chip_bucket_for(n_chips: int) -> int | None:
    """Smallest chip bucket holding ``n_chips``, or None above the top."""
    for bucket in CHIP_BUCKETS:
        if n_chips <= bucket:
            return bucket
    return None


# ---------------------------------------------------------------------------
# Executable builders (lazy jax — only the compile thread runs these)
# ---------------------------------------------------------------------------


def _build_fleet_rollup(key: Any) -> Any:
    import jax
    import jax.numpy as jnp

    from ..analytics.fleet_jax import fleet_rollup

    node_shape, pod_shape = key
    node = jax.ShapeDtypeStruct(tuple(node_shape), jnp.int32)
    pod = jax.ShapeDtypeStruct(tuple(pod_shape), jnp.int32)
    return fleet_rollup.lower(
        node, node, node, node, node, pod, pod, pod, pod
    ).compile()


def _build_region_rollup(key: Any) -> Any:
    import jax
    import jax.numpy as jnp

    from ..analytics.fleet_jax import region_rollup

    node_shape, pod_shape = key
    node = jax.ShapeDtypeStruct(tuple(node_shape), jnp.int32)
    pod = jax.ShapeDtypeStruct(tuple(pod_shape), jnp.int32)
    return region_rollup.lower(
        node, node, node, node, node, node, pod, pod, pod, pod
    ).compile()


def _forecast_avals(bucket: int, length: int, cfg: Any) -> tuple[Any, ...]:
    """(series, weights, prng-key avals, params avals, opt_state avals)
    for the bucketed programs. Params/opt_state come from
    ``jax.eval_shape`` over the real init — the registry can never
    drift from what the model actually carries."""
    import jax
    import jax.numpy as jnp
    import optax

    from .forecast import init_params

    series = jax.ShapeDtypeStruct((bucket, length), jnp.float32)
    weights = jax.ShapeDtypeStruct((bucket,), jnp.float32)
    prng = jax.random.PRNGKey(0)
    key_aval = jax.ShapeDtypeStruct(prng.shape, prng.dtype)
    params = jax.eval_shape(lambda k: init_params(k, cfg), prng)
    opt_state = jax.eval_shape(
        lambda p: optax.adam(cfg.learning_rate).init(p), params
    )
    return series, weights, key_aval, params, opt_state


def _build_bucketed_forecast(name: str, key: Any) -> Any:
    from . import forecast as fc

    bucket, length, cfg, steps, inference, batch_p = key
    series, weights, key_aval, params, opt_state = _forecast_avals(
        bucket, length, cfg
    )
    if name == "forecast.aot_fit_forecast_state":
        lowered = fc._bucketed_fit_forecast_state_program.lower(
            series, weights, key_aval, cfg, steps, inference, batch_p
        )
    else:
        lowered = fc._bucketed_warm_fit_forecast_program.lower(
            series, weights, params, opt_state, cfg, steps, inference, batch_p
        )
    return lowered.compile()


def _build_fused(key: Any) -> Any:
    import jax
    import jax.numpy as jnp

    from . import forecast as fc

    node_shape, pod_shape, bucket, length, cfg, steps, inference, batch_p = key
    node = jax.ShapeDtypeStruct(tuple(node_shape), jnp.int32)
    pod = jax.ShapeDtypeStruct(tuple(pod_shape), jnp.int32)
    series, weights, _key_aval, params, opt_state = _forecast_avals(
        bucket, length, cfg
    )
    lowered = fc.rollup_and_forecast_program.lower(
        node, node, node, node, node, pod, pod, pod, pod,
        series, weights, params, opt_state,
        cfg, steps, inference, batch_p,
    )
    return lowered.compile()


def _build_mesh_rollup(key: Any) -> Any:
    import jax
    import jax.numpy as jnp

    from ..parallel import mesh as mesh_mod

    reducer, dev_shape, node_shape, pod_shape = key
    mesh = mesh_mod.fleet_mesh()
    if tuple(mesh.devices.shape) != tuple(dev_shape):
        raise ValueError(
            f"device topology {tuple(mesh.devices.shape)} != spec {dev_shape}"
        )
    shard = mesh_mod.build_rollup_shard(mesh, reducer, int(node_shape[0]))
    node = jax.ShapeDtypeStruct(tuple(node_shape), jnp.int32)
    pod = jax.ShapeDtypeStruct(tuple(pod_shape), jnp.int32)
    with mesh:
        lowered = jax.jit(shard).lower(
            node, node, node, node, node, pod, pod, pod, pod
        )
        return lowered.compile()


def _build_mesh_region_rollup(key: Any) -> Any:
    import jax
    import jax.numpy as jnp

    from ..parallel import mesh as mesh_mod

    reducer, dev_shape, node_shape, pod_shape = key
    mesh = mesh_mod.fleet_mesh()
    if tuple(mesh.devices.shape) != tuple(dev_shape):
        raise ValueError(
            f"device topology {tuple(mesh.devices.shape)} != spec {dev_shape}"
        )
    n_nodes_pad = int(node_shape[0])
    shard = mesh_mod.build_region_rollup_shard(mesh, reducer, n_nodes_pad)
    node = jax.ShapeDtypeStruct(tuple(node_shape), jnp.int32)
    ext = jax.ShapeDtypeStruct((n_nodes_pad + 1,), jnp.int32)
    pod = jax.ShapeDtypeStruct(tuple(pod_shape), jnp.int32)
    with mesh:
        lowered = jax.jit(shard).lower(
            node, node, node, node, node, node, ext, ext, pod, pod, pod, pod
        )
        return lowered.compile()


_BUILDERS: dict[str, Callable[[Any], Any]] = {
    "analytics.fleet_rollup": _build_fleet_rollup,
    "analytics.region_rollup": _build_region_rollup,
    "mesh.region_rollup": _build_mesh_region_rollup,
    "forecast.aot_fit_forecast_state": lambda key: _build_bucketed_forecast(
        "forecast.aot_fit_forecast_state", key
    ),
    "forecast.aot_warm_fit_forecast": lambda key: _build_bucketed_forecast(
        "forecast.aot_warm_fit_forecast", key
    ),
    "fused.rollup_and_forecast": _build_fused,
    "mesh.rollup": _build_mesh_rollup,
}


def default_specs() -> list[tuple[str, Any]]:
    """The canonical startup set — every hot program at the shapes the
    demo, the bench fixtures, and the SLO engine actually serve. Built
    lazily (imports jax through forecast) so module import stays
    jax-free. ~17 programs, ≈6–9 s of background compile on the CI host
    (the 4k/16k rollup + region-rollup shapes added by ADR-026 are
    element-wise/segment-sum programs, far cheaper per shape than the
    fused forecast, which stays at :data:`FUSED_BUCKETS`) — absorbed
    before the first at-scale request in any realistic startup."""
    import jax

    from .forecast import WARM_STEPS, ForecastConfig

    cfg = ForecastConfig()
    specs: list[tuple[str, Any]] = []
    for node, pod in ROLLUP_BUCKETS:
        specs.append(("analytics.fleet_rollup", ((node,), (pod,))))
        specs.append(("analytics.region_rollup", ((node,), (pod,))))
    for bucket, length in ((64, LIVE_WINDOW_SAMPLES), (8, SLO_SERIES_STEADY)):
        specs.append(
            ("forecast.aot_fit_forecast_state",
             (bucket, length, cfg, 60, "xla", 0))
        )
        specs.append(
            ("forecast.aot_warm_fit_forecast",
             (bucket, length, cfg, WARM_STEPS, "xla", 0))
        )
    for node, pod in FUSED_BUCKETS:
        specs.append(
            ("fused.rollup_and_forecast",
             ((node,), (pod,), 64, LIVE_WINDOW_SAMPLES, cfg, WARM_STEPS,
              "xla", 0))
        )
    specs.append(
        ("mesh.rollup",
         ("psum", (len(jax.devices()),), (256,), (256,)))
    )
    return specs


def _pow2_bucket(n: int, minimum: int = 8) -> int:
    """Pure-python twin of the encoder's ``_bucket`` (power-of-two pad,
    floor ``minimum``) — duplicated here so the coverage check keeps
    module scope stdlib-only. Pinned equal to the encoder's by test."""
    size = minimum
    while size < n:
        size *= 2
    return size


def viewport_bucket_gaps(
    specs: list[tuple[str, Any]] | None = None,
    fleet_sizes: tuple[int, ...] = VIEWPORT_FLEET_SIZES,
) -> list[tuple[str, tuple[int, int]]]:
    """Every (program, (node_pad, pod_pad)) a ``bench_viewport`` fleet
    size needs but ``specs`` does not compile. Empty list == the bucket
    table covers the viewport matrix and no benched paint can pay a
    request-path compile. The startup pass records a non-empty result
    as a compile error (fail-soft, visible on ``/healthz``); the test
    suite asserts it is empty (fail-loud)."""
    if specs is None:
        specs = default_specs()
    have = {(name, key) for name, key in specs}
    gaps: list[tuple[str, tuple[int, int]]] = []
    for n in fleet_sizes:
        pad = _pow2_bucket(n)
        for program in ("analytics.fleet_rollup", "analytics.region_rollup"):
            if (program, ((pad,), (pad,))) not in have:
                gaps.append((program, (pad, pad)))
    return gaps


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------


class AotProgramRegistry:
    """Compiled-executable store keyed ``(program name, signature)`` —
    the signature IS the ledger's recompile key, so startup compiles
    and request dispatches land on the same ledger row.

    Thread-safety: the lock guards the program dict and counters;
    compiles happen outside it (a compile is seconds, a lookup must be
    nanoseconds). ``perf`` is the injectable duration seam (ADR-013
    clock audit); ``specs`` overrides the startup set for tests."""

    def __init__(
        self,
        *,
        specs: list[tuple[str, Any]] | None = None,
        perf: Callable[[], float] = time.perf_counter,
    ) -> None:
        self._lock = threading.Lock()
        self._perf = perf
        self._specs = specs
        self._programs: dict[tuple[str, Any], Any] = {}
        self._pending: set[tuple[str, Any]] = set()
        self._state = "idle"  # idle | compiling | ready | unavailable
        self._ready_event = threading.Event()
        self._thread: threading.Thread | None = None
        self.last_error: str | None = None
        # Monotone ints (flight/healthz counters view — r10-review rule).
        self.programs_compiled = 0
        self.compile_errors = 0
        self.exec_failures = 0
        self.bucket_hits = 0
        self.bucket_misses = 0
        self.donation_saved_bytes = 0
        self.compile_ms_total = 0.0

    # -- startup ---------------------------------------------------------

    def compile_startup(self, *, block: bool = False) -> None:
        """Kick off (or, idempotently, skip) the startup compile pass.
        ``block=True`` runs it inline — tests and the bench's warmup
        use it; ``serve()`` uses the default daemon thread so listening
        starts immediately and early requests just miss (plain path)."""
        with self._lock:
            if self._state != "idle":
                return
            self._state = "compiling"
        if block:
            self._compile_all()
            return
        self._thread = threading.Thread(
            target=self._compile_all, name="aot-startup-compile", daemon=True
        )
        self._thread.start()

    def _compile_all(self) -> None:
        try:
            specs = self._specs if self._specs is not None else default_specs()
        except Exception as exc:  # noqa: BLE001 — jax-less host
            self.last_error = f"{type(exc).__name__}: {exc}"[:200]
            with self._lock:
                self._state = "unavailable"
            self._ready_event.set()
            return
        if self._specs is None:
            # ADR-026 startup assertion: the default bucket table must
            # cover every bench_viewport fleet size. Fail-soft at
            # runtime (serving still works, the plain jit path pays the
            # compile) but loudly surfaced — and the test suite asserts
            # the gap list is empty, which is where a bucket-table
            # regression actually fails.
            gaps = viewport_bucket_gaps(specs)
            if gaps:
                self.compile_errors += 1
                self.last_error = f"viewport buckets uncovered: {gaps}"[:200]
        for name, key in specs:
            self._compile_one(name, key)
        with self._lock:
            self._state = "ready"
        self._ready_event.set()

    def _compile_one(self, name: str, key: Any) -> None:
        """lower+compile one program, ledger-tracked as a STARTUP-phase
        compile under the exact (name, key) the request path will use.
        A failed build is recorded (never raised): the corresponding
        request-side lookups miss and the plain jitted path serves."""
        builder = _BUILDERS.get(name)
        if builder is None:
            self.compile_errors += 1
            self.last_error = f"no builder for {name!r}"
            return
        t0 = self._perf()
        try:
            with _jaxcost.track(name, key, phase="startup"):
                exe = builder(key)
        except Exception as exc:  # noqa: BLE001 — a miss, never an error
            self.compile_errors += 1
            self.last_error = f"{name}: {type(exc).__name__}: {exc}"[:200]
            return
        elapsed_ms = (self._perf() - t0) * 1000.0
        with self._lock:
            self._programs[(name, key)] = exe
            self.programs_compiled += 1
            self.compile_ms_total += elapsed_ms

    # -- request-side lookups --------------------------------------------

    def ready(self) -> bool:
        return self._state == "ready"

    @property
    def state(self) -> str:
        return self._state

    def wait_ready(self, timeout: float | None = None) -> bool:
        """Block until the startup pass finished (either outcome).
        Benches and tests use it; serving never does."""
        return self._ready_event.wait(timeout)

    def executable(self, name: str, key: Any) -> Any | None:
        """The compiled executable for exactly ``(name, key)``, or None
        (a counted bucket miss). Callers gate on :meth:`ready` first so
        the miss counters mean "no bucket covers this shape", not
        "startup hasn't finished"."""
        with self._lock:
            exe = self._programs.get((name, key))
            if exe is None:
                self.bucket_misses += 1
            else:
                self.bucket_hits += 1
        return exe

    def note_bucket_miss(self, name: str) -> None:  # noqa: ARG002 — name kept for future per-program split
        """A shape no bucket can hold (e.g. chip count above the top
        bucket) — counted without a dict lookup."""
        with self._lock:
            self.bucket_misses += 1

    def note_donation(self, n_bytes: int) -> None:
        """Account bytes a donated call let XLA reuse in place."""
        with self._lock:
            self.donation_saved_bytes += int(n_bytes)

    def note_exec_failure(self, name: str, reason: str) -> None:
        """A compiled executable raised at call time (shape drift,
        deleted donated buffer). The caller falls back to the plain
        path; the failure is surfaced, never silent."""
        with self._lock:
            self.exec_failures += 1
            self.last_error = f"{name}: {reason}"[:200]

    # -- background backfill ---------------------------------------------

    def ensure(self, name: str, key: Any) -> bool:
        """Schedule a background compile for ``(name, key)`` unless it
        is already compiled or in flight. Returns True when a compile
        was scheduled. Serving never blocks on it: the current request
        misses (plain path), later ones hit."""
        with self._lock:
            if self._state in ("idle", "unavailable"):
                return False
            pair = (name, key)
            if pair in self._programs or pair in self._pending:
                return False
            self._pending.add(pair)

        def _run() -> None:
            try:
                self._compile_one(name, key)
            finally:
                with self._lock:
                    self._pending.discard((name, key))

        threading.Thread(
            target=_run, name="aot-backfill-compile", daemon=True
        ).start()
        return True

    def ensure_rollup_shapes(self, node_pad: int, pod_pad: int) -> None:
        """Observed-shape backfill hook, called from the device-cache
        warm path: whatever (node, pod) buckets the live fleet actually
        encodes to get their rollup executable compiled off the request
        path, even when they match no default spec. The viewport region
        rollup (ADR-026) shares the (node, pod) key, so one observed
        shape warms both programs."""
        self.ensure("analytics.fleet_rollup", ((node_pad,), (pod_pad,)))
        self.ensure("analytics.region_rollup", ((node_pad,), (pod_pad,)))

    # -- read surfaces ---------------------------------------------------

    def counters(self) -> dict[str, int]:
        """Monotone ints, lock-free — the flight recorder's per-request
        delta view (r10-review rule)."""
        return {
            "programs_compiled": self.programs_compiled,
            "compile_errors": self.compile_errors,
            "exec_failures": self.exec_failures,
            "bucket_hits": self.bucket_hits,
            "bucket_misses": self.bucket_misses,
            "donation_saved_bytes": self.donation_saved_bytes,
        }

    def snapshot(self) -> dict[str, Any]:
        """``/healthz`` ``runtime.jax.aot`` block."""
        with self._lock:
            programs = sorted(name for name, _key in self._programs)
        return {
            "state": self._state,
            "programs_compiled": self.programs_compiled,
            "compile_errors": self.compile_errors,
            "exec_failures": self.exec_failures,
            "bucket_hits": self.bucket_hits,
            "bucket_misses": self.bucket_misses,
            "donation_saved_bytes": self.donation_saved_bytes,
            "compile_ms_total": round(self.compile_ms_total, 1),
            "last_error": self.last_error,
            "programs": programs,
        }


#: The process registry. set_registry swaps it for tests; call sites
#: read through the accessor so they always hit the live instance.
_REGISTRY = AotProgramRegistry()


def registry() -> AotProgramRegistry:
    return _REGISTRY


def set_registry(instance: AotProgramRegistry) -> AotProgramRegistry:
    """Install ``instance`` as the process registry; returns the one it
    replaced so tests can restore."""
    global _REGISTRY
    previous, _REGISTRY = _REGISTRY, instance
    return previous


# AOT registry state as scrapeable gauges (ADR-013): callback views
# through the accessor — /metricsz and /healthz read the SAME counters,
# and a test-swapped registry is reflected everywhere at once.
_metrics_registry.gauge_fn(
    "headlamp_tpu_aot_programs_compiled_count",
    "Executables the AOT registry holds (startup specs + backfills)",
    lambda: float(registry().programs_compiled),
)
_metrics_registry.gauge_fn(
    "headlamp_tpu_aot_bucket_hits_total",
    "Request-path lookups served by a precompiled bucketed executable",
    lambda: float(registry().bucket_hits),
)
_metrics_registry.gauge_fn(
    "headlamp_tpu_aot_bucket_misses_total",
    "Request-path lookups no bucket covered (plain jit fallback ran)",
    lambda: float(registry().bucket_misses),
)
_metrics_registry.gauge_fn(
    "headlamp_tpu_aot_donation_saved_bytes_total",
    "Buffer bytes donated calls let XLA reuse in place",
    lambda: float(registry().donation_saved_bytes),
)
