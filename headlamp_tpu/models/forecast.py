"""Utilization forecaster: windows of chip telemetry → near-future load.

Architecture notes (TPU-first):
- Three dense layers; matmuls run in **bfloat16** with float32
  accumulation/params — the MXU-native precision recipe.
- Static shapes everywhere; the whole train step jits to one program.
- Sharding: batch over the ``data`` mesh axis, hidden features over
  ``model`` (see :func:`param_shardings`); XLA/GSPMD inserts the
  collectives (all-reduce of activations/grads) from the annotations
  alone — no hand-written collectives in the model.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..obs.jaxcost import track as _jax_track

Params = dict[str, jax.Array]


@dataclass(frozen=True)
class ForecastConfig:
    window: int = 32      #: history samples per example
    hidden: int = 128     #: hidden width (MXU-friendly multiple of 128)
    horizon: int = 8      #: future samples predicted
    learning_rate: float = 1e-3


def init_params(key: jax.Array, cfg: ForecastConfig) -> Params:
    k1, k2, k3 = jax.random.split(key, 3)

    def glorot(k: jax.Array, shape: tuple[int, int]) -> jax.Array:
        scale = jnp.sqrt(2.0 / (shape[0] + shape[1]))
        return jax.random.normal(k, shape, dtype=jnp.float32) * scale

    return {
        "w1": glorot(k1, (cfg.window, cfg.hidden)),
        "b1": jnp.zeros((cfg.hidden,), jnp.float32),
        "w2": glorot(k2, (cfg.hidden, cfg.hidden)),
        "b2": jnp.zeros((cfg.hidden,), jnp.float32),
        "w3": glorot(k3, (cfg.hidden, cfg.horizon)),
        "b3": jnp.zeros((cfg.horizon,), jnp.float32),
    }


def _dense_bf16(x: jax.Array, w: jax.Array, b: jax.Array) -> jax.Array:
    """bf16 matmul, f32 accumulate+bias — the MXU precision pattern."""
    y = jax.lax.dot_general(
        x.astype(jnp.bfloat16),
        w.astype(jnp.bfloat16),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return y + b


def forward(params: Params, x: jax.Array) -> jax.Array:
    """[batch, window] -> [batch, horizon] utilization fractions.
    Output squashed to [0, 1] — utilization can't leave that range."""
    h = jax.nn.gelu(_dense_bf16(x, params["w1"], params["b1"]))
    h = jax.nn.gelu(_dense_bf16(h, params["w2"], params["b2"]))
    return jax.nn.sigmoid(_dense_bf16(h, params["w3"], params["b3"]))


def loss_fn(params: Params, x: jax.Array, y: jax.Array) -> jax.Array:
    pred = forward(params, x)
    return jnp.mean((pred - y) ** 2)


def make_train_step(
    cfg: ForecastConfig,
) -> tuple[Callable[..., Any], optax.GradientTransformation]:
    """(jitted train_step, optimizer). ``train_step(params, opt_state,
    x, y) -> (params, opt_state, loss)`` — one fused XLA program."""
    optimizer = optax.adam(cfg.learning_rate)

    @jax.jit
    def train_step(
        params: Params, opt_state: Any, x: jax.Array, y: jax.Array
    ) -> tuple[Params, Any, jax.Array]:
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step, optimizer


def param_shardings(mesh: Mesh) -> dict[str, NamedSharding]:
    """dp×tp layout: w1 columns / w2 rows over ``model`` (megatron-style
    pairing keeps the activation all-reduce to one per block); the output
    projection replicated (horizon is tiny)."""
    s = lambda *spec: NamedSharding(mesh, P(*spec))  # noqa: E731
    return {
        "w1": s(None, "model"),
        "b1": s("model"),
        "w2": s("model", None),
        "b2": s(None),
        "w3": s(None),
        "b3": s(None),
    }


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("data", None))


# ---------------------------------------------------------------------------
# Synthetic telemetry (deterministic; demos/tests/benches)
# ---------------------------------------------------------------------------

def synthetic_telemetry(
    n_series: int, length: int, key: jax.Array | None = None
) -> jax.Array:
    """[n_series, length] utilization traces: per-chip base load + two
    harmonics + noise, clipped to [0,1]. Deterministic under a fixed
    key so fixtures and benches reproduce."""
    key = key if key is not None else jax.random.PRNGKey(20260729)
    k_base, k_phase, k_noise = jax.random.split(key, 3)
    t = jnp.arange(length, dtype=jnp.float32)
    base = jax.random.uniform(k_base, (n_series, 1), minval=0.25, maxval=0.7)
    phase = jax.random.uniform(k_phase, (n_series, 2), maxval=2 * jnp.pi)
    wave = 0.18 * jnp.sin(t[None, :] / 17.0 + phase[:, :1]) + 0.09 * jnp.sin(
        t[None, :] / 5.0 + phase[:, 1:]
    )
    noise = 0.04 * jax.random.normal(k_noise, (n_series, length))
    return jnp.clip(base + wave + noise, 0.0, 1.0)


def make_windows(
    series: jax.Array, window: int, horizon: int
) -> tuple[jax.Array, jax.Array]:
    """Sliding (x, y) examples from [n_series, length] traces, flattened
    across series. Static-shape unfold via gather indices (no Python
    loop over positions)."""
    n_series, length = series.shape
    n_pos = length - window - horizon + 1
    if n_pos <= 0:
        raise ValueError("series shorter than window + horizon")
    starts = jnp.arange(n_pos)
    x_idx = starts[:, None] + jnp.arange(window)[None, :]
    y_idx = starts[:, None] + window + jnp.arange(horizon)[None, :]
    x = series[:, x_idx].reshape(n_series * n_pos, window)
    y = series[:, y_idx].reshape(n_series * n_pos, horizon)
    return x, y


class InferenceDispatch(NamedTuple):
    """Which inference path actually served a forecast — observability
    for the silent-fallback policy (a Pallas kernel broken by a jax
    upgrade must show up in /healthz-adjacent surfaces and the bench,
    not vanish behind the XLA fallback)."""

    path: str                        #: "pallas[-warm]" | "xla[-warm]" | "repeat"
    fallback_reason: str | None = None  #: set when Pallas was tried and failed
    #: Final training MSE of the fit, as a DEVICE scalar (None on the
    #: persistence path) — callers materialize it together with the
    #: predictions in one device_get; a separate float() would cost an
    #: extra round-trip over a tunneled chip. The incremental entry
    #: (:func:`fit_and_forecast_incremental`) sets it to a HOST float
    #: instead — the demotion check already paid the fetch.
    fit_mse: Any = None
    #: Generation of the :class:`WarmState` this fit refined (ADR-015).
    #: Set on warm fits AND on demoted-to-cold fits (the carry was
    #: consulted either way); None on a from-scratch cold fit.
    carried_from_generation: int | None = None
    #: Why a warm refinement was thrown away for a cold refit — the
    #: never-silent half of the demotion policy (same contract as the
    #: Pallas ``fallback_reason``). None unless a demotion happened.
    warm_demotion_reason: str | None = None
    #: What the fit trained on (ADR-018 auditability): "live-window"
    #: for a fresh Prometheus range query, "history" for the captured
    #: in-process tier. Stamped by the service layer (the fit itself is
    #: source-blind); defaulted here so every construction site and
    #: pickled carry stays valid.
    data_source: str = "live-window"

    @property
    def used_pallas(self) -> bool:
        return self.path in ("pallas", "pallas-warm")

    @property
    def warm(self) -> bool:
        return self.path.endswith("-warm")


def forecast_next_with_dispatch(
    params: Params, recent: jax.Array, cfg: ForecastConfig | None = None
) -> tuple[jax.Array, InferenceDispatch]:
    """Pages' inference entry: [n_chips, window] recent samples ->
    ([n_chips, horizon] predicted utilization, dispatch record).

    Dispatch: on a TPU backend the fused Pallas kernel serves inference
    (``pallas_forward.forecast_forward_pallas`` — every intermediate
    stays in VMEM); elsewhere the plain XLA ``forward``. Any Pallas
    failure falls back to XLA — the kernel is an optimization, never a
    dependency — but the failure is RECORDED in the returned dispatch,
    never swallowed invisibly."""
    if jax.devices()[0].platform == "tpu":
        try:
            from .pallas_forward import forecast_forward_pallas

            out = forecast_forward_pallas(params, recent, cfg, interpret=False)
            return out, InferenceDispatch("pallas")
        except Exception as exc:  # noqa: BLE001 — optimization, not a dependency
            reason = f"{type(exc).__name__}: {exc}"[:200]
            return forward(params, recent), InferenceDispatch("xla", reason)
    return forward(params, recent), InferenceDispatch("xla")


def forecast_next(
    params: Params, recent: jax.Array, cfg: ForecastConfig | None = None
) -> jax.Array:
    """:func:`forecast_next_with_dispatch` without the record, for
    callers that only want the numbers."""
    out, _ = forecast_next_with_dispatch(params, recent, cfg)
    return out


@partial(jax.jit, static_argnames=("cfg", "steps"))
def _warm_fit_program(
    series: jax.Array,
    params: Params,
    opt_state: Any,
    cfg: ForecastConfig,
    steps: int,
) -> tuple[Params, Any, jax.Array]:
    """windowing → ``steps`` optimizer steps (lax.scan) from the GIVEN
    ``(params, opt_state)`` → (params, opt_state, final training MSE),
    as ONE XLA program. This is THE training program: the cold fit is
    exactly this program seeded from a fresh init (see
    :func:`_fit_program_with_state`), so warm refinement and cold fit
    can never train different models — there is only one scan body. A
    Python training loop would issue one device dispatch per step —
    tens of round-trips on a remote/tunneled TPU for a fit the fused
    program finishes in a single dispatch; the windowing
    (``make_windows``'s gathers) is fused in too."""
    x, y = make_windows(series, cfg.window, cfg.horizon)
    optimizer = optax.adam(cfg.learning_rate)

    def body(
        carry: tuple[Params, Any], _: None
    ) -> tuple[tuple[Params, Any], jax.Array]:
        p, s = carry
        loss, grads = jax.value_and_grad(loss_fn)(p, x, y)
        updates, s = optimizer.update(grads, s, p)
        p = optax.apply_updates(p, updates)
        return (p, s), loss

    (params, opt_state), _ = jax.lax.scan(
        body, (params, opt_state), None, length=steps
    )
    # Self-assessment of the RETURNED model: scan losses are computed
    # before each update, so losses[-1] would describe the penultimate
    # params. One more loss_fn at the final params stays in the fused
    # program — negligible next to the scan.
    return params, opt_state, loss_fn(params, x, y)


@partial(jax.jit, static_argnames=("cfg", "steps"))
def _fit_program_with_state(
    series: jax.Array,
    key: jax.Array,
    cfg: ForecastConfig,
    steps: int,
) -> tuple[Params, Any, jax.Array]:
    """Cold fit that also surfaces the optimizer state: fresh init →
    the shared training scan (nested jit inlines into this trace) →
    (params, opt_state, final MSE). The state is what ADR-015's warm
    starts carry across TTL windows."""
    params = init_params(key, cfg)
    opt_state = optax.adam(cfg.learning_rate).init(params)
    return _warm_fit_program(series, params, opt_state, cfg, steps)


@partial(jax.jit, static_argnames=("cfg", "steps"))
def _fit_program(
    series: jax.Array,
    key: jax.Array,
    cfg: ForecastConfig,
    steps: int,
) -> tuple[Params, jax.Array]:
    """(fitted params, final training MSE) — the historical cold-fit
    surface (bench parity checks use it), now a thin view over
    :func:`_fit_program_with_state`."""
    params, _, final_loss = _fit_program_with_state(series, key, cfg, steps)
    return params, final_loss


def _infer_recent(
    params: Params, series: jax.Array, cfg: ForecastConfig,
    inference: str, batch_p: int,
) -> jax.Array:
    """Inference stage shared by every fused program: predict the next
    horizon from each trace's latest window, via the Pallas kernel or
    XLA forward (chosen statically at trace time)."""
    recent = series[:, -cfg.window:]
    if inference == "pallas":
        from .pallas_forward import forecast_forward_padded

        return forecast_forward_padded(
            params, recent, batch_p=batch_p, horizon=cfg.horizon, interpret=False
        )
    return forward(params, recent)


@partial(jax.jit, static_argnames=("cfg", "steps", "inference", "batch_p"))
def _warm_fit_forecast_program(
    series: jax.Array,
    params: Params,
    opt_state: Any,
    cfg: ForecastConfig,
    steps: int,
    inference: str,
    batch_p: int,
) -> tuple[jax.Array, Params, Any, jax.Array]:
    """Warm refinement + inference as ONE XLA program / ONE dispatch:
    windowing → short refinement scan from the carried ``(params,
    opt_state)`` → inference, returning ``(predictions, params,
    opt_state, final MSE)`` so the caller can carry the refined state
    into the next TTL window. The fit is :func:`_warm_fit_program`
    itself — nested jit inlines into this trace, so warm serving and
    the standalone warm fit can never train different models."""
    params, opt_state, final_loss = _warm_fit_program(
        series, params, opt_state, cfg, steps
    )
    out = _infer_recent(params, series, cfg, inference, batch_p)
    return out, params, opt_state, final_loss


@partial(jax.jit, static_argnames=("cfg", "steps", "inference", "batch_p"))
def _fit_forecast_state_program(
    series: jax.Array,
    key: jax.Array,
    cfg: ForecastConfig,
    steps: int,
    inference: str,
    batch_p: int,
) -> tuple[jax.Array, Params, Any, jax.Array]:
    """Cold fit + inference as ONE program, also surfacing the fitted
    ``(params, opt_state)`` for the ADR-015 warm-start carry. Built on
    :func:`_fit_program_with_state` (fresh init → the shared training
    scan), so this and :func:`_warm_fit_forecast_program` train via the
    SAME scan body."""
    params, opt_state, final_loss = _fit_program_with_state(series, key, cfg, steps)
    out = _infer_recent(params, series, cfg, inference, batch_p)
    return out, params, opt_state, final_loss


@partial(jax.jit, static_argnames=("cfg", "steps", "inference", "batch_p"))
def _fit_forecast_program(
    series: jax.Array,
    key: jax.Array,
    cfg: ForecastConfig,
    steps: int,
    inference: str,
    batch_p: int,
) -> tuple[jax.Array, jax.Array]:
    """The WHOLE forecast — windowing → fit scan → inference (Pallas
    kernel or XLA forward, chosen statically) — as ONE XLA program and
    therefore ONE device dispatch. The split fit/infer path costs two
    dispatches; over a tunneled/remote TPU each round-trip is ~50-70 ms
    (BENCH_r03 measured the rollup's dispatch at ~150 ms end-to-end),
    so fusing the pair nearly halves the serving-path forecast cost.

    Thin view over :func:`_fit_forecast_state_program` for callers that
    don't carry warm state."""
    out, _, _, final_loss = _fit_forecast_state_program(
        series, key, cfg, steps, inference, batch_p
    )
    return out, final_loss


def fit_and_forecast_with_dispatch(
    series: jax.Array,
    cfg: ForecastConfig | None = None,
    *,
    steps: int = 60,
    seed: int = 0,
) -> tuple[jax.Array, InferenceDispatch]:
    """Online fit on the given traces, then predict the next horizon
    from each trace's latest window: [n_chips, T] -> ([n_chips, horizon],
    dispatch record). Fit AND inference run as one fused program
    (:func:`_fit_forecast_program`) — the Pallas kernel inlined on a TPU
    backend, plain XLA elsewhere; any Pallas failure falls back to the
    fused XLA variant with the reason recorded.

    There is no pre-trained checkpoint by design — utilization dynamics
    are cluster-specific, the model is tiny, and fitting on exactly the
    window the page displays keeps the prediction honest. Traces shorter
    than window+horizon fall back to persistence (repeat last value)."""
    cfg = cfg or ForecastConfig()
    series = jnp.asarray(series, dtype=jnp.float32)
    n_chips, length = series.shape
    if length < cfg.window + cfg.horizon:
        # Persistence fallback: no kernel ran at all, and the dispatch
        # record must say so — not claim an XLA inference that never
        # happened.
        last = series[:, -1:]
        return jnp.repeat(last, cfg.horizon, axis=1), InferenceDispatch("repeat")

    key = jax.random.PRNGKey(seed)
    if jax.devices()[0].platform == "tpu" and _pallas_broken_reason is None:
        try:
            from .pallas_forward import check_single_tile, pallas_batch_p

            check_single_tile(cfg.window, cfg.hidden, cfg.horizon)
            batch_p = pallas_batch_p(n_chips)
            # ADR-019 cost ledger: the signature is jax's recompile key
            # (input shape + every static arg) so first-call compiles
            # and warm dispatches classify exactly.
            with _jax_track(
                "forecast.fit_forecast",
                (series.shape, cfg, steps, "pallas", batch_p),
            ):
                out, mse = _fit_forecast_program(
                    series, key, cfg, steps, "pallas", batch_p
                )
            return out, InferenceDispatch("pallas", fit_mse=mse)
        except Exception as exc:  # noqa: BLE001 — optimization, not a dependency
            # Memoize: a kernel that failed to lower/compile would
            # otherwise re-pay the failed compile on EVERY forecast.
            _record_pallas_broken(f"{type(exc).__name__}: {exc}"[:200])
    # ADR-020: the cold XLA fit serves from a registry-precompiled
    # bucketed executable when one covers this shape (fitted state is
    # simply dropped — this entry doesn't carry it); a miss runs the
    # plain jitted program exactly as before.
    aot_result = _try_aot_forecast(
        _fit_forecast_state_program, (series, key, cfg, steps), "xla", 0
    )
    if aot_result is not None:
        out, _params, _opt_state, mse = aot_result
        return out, InferenceDispatch("xla", _pallas_broken_reason, fit_mse=mse)
    with _jax_track(
        "forecast.fit_forecast", (series.shape, cfg, steps, "xla", 0)
    ):
        out, mse = _fit_forecast_program(series, key, cfg, steps, "xla", 0)
    return out, InferenceDispatch("xla", _pallas_broken_reason, fit_mse=mse)


#: Once the fused Pallas variant fails, the reason is memoized and every
#: later forecast serves the fused-XLA variant immediately — recorded in
#: each dispatch (and thus the page + bench), reset only per process.
_pallas_broken_reason: str | None = None


def _record_pallas_broken(reason: str) -> None:
    global _pallas_broken_reason
    _pallas_broken_reason = reason


def fit_and_forecast(
    series: jax.Array,
    cfg: ForecastConfig | None = None,
    *,
    steps: int = 60,
    seed: int = 0,
) -> jax.Array:
    """:func:`fit_and_forecast_with_dispatch` without the record."""
    out, _ = fit_and_forecast_with_dispatch(series, cfg, steps=steps, seed=seed)
    return out


# ---------------------------------------------------------------------------
# Warm-start incremental fitting (ADR-015)
# ---------------------------------------------------------------------------

#: Refinement steps for a warm fit. The carried params already sit near
#: a minimum for this fleet's dynamics; ~1/6 of the cold budget tracks
#: the drift between TTL windows.
WARM_STEPS = 10

#: A warm fit whose final MSE exceeds ``tolerance × last cold MSE``
#: self-demotes to a cold refit. 2× leaves headroom for ordinary drift
#: while catching a carry that has gone stale (fleet regime change,
#: optimizer state poisoned by a pathological window).
COLD_MSE_TOLERANCE = 2.0

#: Absolute MSE floor for the demotion comparison: near-zero cold MSEs
#: (flat synthetic traces) would otherwise make ANY warm fit look like a
#: regression by ratio alone.
_DEMOTION_MSE_FLOOR = 1e-4


class WarmState(NamedTuple):
    """Fitted params + optimizer state carried across TTL windows, keyed
    by fleet content (the caller owns the keying — see
    ``DashboardApp._metrics_key``). ``cold_mse`` anchors the demotion
    check; ``generation`` counts cold fits for this key so dispatch
    records can say which lineage a warm fit refined."""

    params: Params
    opt_state: Any
    cold_mse: float        #: host float — the fetch was already paid
    generation: int        #: increments on every cold (re)fit
    cfg: ForecastConfig    #: carry is invalid if the caller's cfg changed
    n_chips: int           #: param shapes are chip-count-independent, but
                           #: a fleet resize means different dynamics


def _platform_and_pallas(
    cfg: ForecastConfig, n_chips: int
) -> tuple[str, int, str | None]:
    """Resolve the inference path exactly like the cold entry: returns
    ``(inference, batch_p, fallback_reason)`` — ``("pallas", p, None)``
    on a healthy TPU backend, else ``("xla", 0, reason-or-None)``."""
    if jax.devices()[0].platform == "tpu" and _pallas_broken_reason is None:
        try:
            from .pallas_forward import check_single_tile, pallas_batch_p

            check_single_tile(cfg.window, cfg.hidden, cfg.horizon)
            return "pallas", pallas_batch_p(n_chips), None
        except Exception as exc:  # noqa: BLE001 — optimization, not a dependency
            _record_pallas_broken(f"{type(exc).__name__}: {exc}"[:200])
    return "xla", 0, _pallas_broken_reason


def fit_and_forecast_incremental(
    series: jax.Array,
    cfg: ForecastConfig | None = None,
    *,
    state: WarmState | None = None,
    steps: int = 60,
    warm_steps: int = WARM_STEPS,
    seed: int = 0,
    cold_mse_tolerance: float = COLD_MSE_TOLERANCE,
) -> tuple[jax.Array, InferenceDispatch, WarmState | None]:
    """Warm-start entry: refine the carried :class:`WarmState` with a
    short scan instead of refitting from scratch, falling back (and
    RECORDING why) whenever the carry can't be trusted.

    Returns ``(predictions, dispatch, new_state)``. The dispatch's
    ``fit_mse`` is a HOST float here — the demotion check must compare
    MSEs on the host anyway, so the predictions+MSE materialization is
    paid once inside this call (one device_get), not deferred.

    Demotion policy (never silent, same contract as the Pallas
    fallback): a warm fit whose final MSE exceeds
    ``cold_mse_tolerance × max(cold_mse, floor)`` is thrown away and a
    cold refit runs, with ``warm_demotion_reason`` set in the dispatch.
    A cfg/fleet-shape mismatch or a warm-program exception demotes the
    same way. The persistence ("repeat") path passes the state through
    untouched — a too-short window says nothing about the carry."""
    cfg = cfg or ForecastConfig()
    series = jnp.asarray(series, dtype=jnp.float32)
    n_chips, length = series.shape
    if length < cfg.window + cfg.horizon:
        last = series[:, -1:]
        preds = jnp.repeat(last, cfg.horizon, axis=1)
        return preds, InferenceDispatch("repeat"), state

    inference, batch_p, fallback = _platform_and_pallas(cfg, n_chips)

    def _run_fused(program: Callable[..., Any], *head: Any) -> Any:
        """Run a fused state program on the resolved path; a Pallas
        failure memoizes the breakage and re-runs on XLA (the same
        optimization-never-dependency policy as the cold entry), so
        only genuine training failures escape to the caller."""
        nonlocal inference, batch_p, fallback
        # ADR-019 cost ledger: name from the program, signature from
        # jax's recompile key (input shape + hashable static args).
        name = "forecast." + getattr(program, "__name__", "program").lstrip("_")

        def sig(inf: str, bp: int) -> tuple:
            return (
                tuple(head[0].shape),
                *(
                    h
                    for h in head[1:]
                    if isinstance(h, (int, float, str, ForecastConfig))
                ),
                inf,
                bp,
            )

        # ADR-020: a registry-precompiled bucketed executable serves
        # first when one covers this shape; a miss (None) runs the
        # plain jitted program exactly as before.
        aot_result = _try_aot_forecast(program, head, inference, batch_p)
        if aot_result is not None:
            return aot_result
        try:
            with _jax_track(name, sig(inference, batch_p)):
                return program(*head, inference, batch_p)
        except Exception as exc:  # noqa: BLE001
            if inference != "pallas":
                raise
            _record_pallas_broken(f"{type(exc).__name__}: {exc}"[:200])
            inference, batch_p, fallback = "xla", 0, _pallas_broken_reason
            with _jax_track(name, sig("xla", 0)):
                return program(*head, "xla", 0)

    demotion: str | None = None
    carried_gen: int | None = None
    if state is not None:
        carried_gen = state.generation
        if state.cfg != cfg or state.n_chips != n_chips:
            demotion = (
                f"carry mismatch: cfg/fleet changed "
                f"(chips {state.n_chips}->{n_chips})"
            )
        else:
            try:
                out, params, opt_state, mse_dev = _run_fused(
                    _warm_fit_forecast_program,
                    series, state.params, state.opt_state, cfg, warm_steps,
                )
                # One host round-trip for everything the caller and the
                # demotion check need (ADR-012 funnel discipline).
                preds_host, warm_mse = jax.device_get((out, mse_dev))
                warm_mse = float(warm_mse)
            except Exception as exc:  # noqa: BLE001 — carry is an optimization
                demotion = f"warm program failed: {type(exc).__name__}: {exc}"[:200]
            else:
                bound = cold_mse_tolerance * max(state.cold_mse, _DEMOTION_MSE_FLOOR)
                if warm_mse > bound:
                    demotion = (
                        f"warm mse {warm_mse:.3g} > {cold_mse_tolerance:g}x "
                        f"cold {state.cold_mse:.3g}"
                    )
                else:
                    new_state = WarmState(
                        params, opt_state, state.cold_mse,
                        state.generation, cfg, n_chips,
                    )
                    dispatch = InferenceDispatch(
                        f"{inference}-warm", fallback, fit_mse=warm_mse,
                        carried_from_generation=state.generation,
                    )
                    return preds_host, dispatch, new_state

    # Cold fit — from scratch, or demoted from a rejected warm attempt.
    key = jax.random.PRNGKey(seed)
    out, params, opt_state, mse_dev = _run_fused(
        _fit_forecast_state_program, series, key, cfg, steps
    )
    preds_host, cold_mse = jax.device_get((out, mse_dev))
    cold_mse = float(cold_mse)
    generation = (state.generation + 1) if state is not None else 0
    new_state = WarmState(params, opt_state, cold_mse, generation, cfg, n_chips)
    dispatch = InferenceDispatch(
        inference, fallback, fit_mse=cold_mse,
        carried_from_generation=carried_gen,
        warm_demotion_reason=demotion,
    )
    return preds_host, dispatch, new_state


# ---------------------------------------------------------------------------
# Bucketed programs for the AOT registry (ADR-020)
# ---------------------------------------------------------------------------
#
# The plain fused programs above recompile per exact (n_chips, length)
# shape, so the first request at any new fleet size pays trace+compile
# on the request path. The bucketed twins below take the chip axis at a
# small set of canonical sizes (``models.aot.CHIP_BUCKETS``) with a
# per-chip weight vector masking the padding rows, so the AOT registry
# can lower+compile them once at startup and arbitrary fleet sizes hit
# a precompiled executable. The masked loss is analytically identical
# to the plain mean when every weight is 1 (each chip contributes the
# same number of sliding-window examples), and padded rows contribute
# exactly zero gradient — pinned by tests/test_aot.py.


def _masked_loss_fn(
    params: Params, x: jax.Array, y: jax.Array, w: jax.Array
) -> jax.Array:
    """:func:`loss_fn` with a per-example weight vector: padded chips
    carry weight 0 so they never leak into the fit."""
    pred = forward(params, x)
    per_example = jnp.mean((pred - y) ** 2, axis=1)
    return jnp.sum(per_example * w) / jnp.maximum(jnp.sum(w), 1.0)


def _bucketed_fit_body(
    series: jax.Array,
    chip_weights: jax.Array,
    params: Params,
    opt_state: Any,
    cfg: ForecastConfig,
    steps: int,
    inference: str,
    batch_p: int,
) -> tuple[jax.Array, Params, Any, jax.Array]:
    """Masked twin of :func:`_warm_fit_forecast_program`'s body:
    windowing → weighted refinement scan → inference over the PADDED
    chip axis. ``chip_weights[c]`` is 1.0 for real chips, 0.0 for
    padding; each chip's ``n_pos`` sliding examples inherit its weight
    (make_windows flattens series-major, so ``repeat`` lines up)."""
    x, y = make_windows(series, cfg.window, cfg.horizon)
    n_pos = x.shape[0] // series.shape[0]
    w = jnp.repeat(chip_weights, n_pos)
    optimizer = optax.adam(cfg.learning_rate)

    def body(
        carry: tuple[Params, Any], _: None
    ) -> tuple[tuple[Params, Any], jax.Array]:
        p, s = carry
        loss, grads = jax.value_and_grad(_masked_loss_fn)(p, x, y, w)
        updates, s = optimizer.update(grads, s, p)
        p = optax.apply_updates(p, updates)
        return (p, s), loss

    (params, opt_state), _ = jax.lax.scan(
        body, (params, opt_state), None, length=steps
    )
    out = _infer_recent(params, series, cfg, inference, batch_p)
    return out, params, opt_state, _masked_loss_fn(params, x, y, w)


#: Warm refinement at a canonical bucket, with the (params, opt_state)
#: carry DONATED: the caller replaces the carry with the returned pair,
#: so XLA overwrites the optimizer state in place instead of allocating
#: fresh outputs. The padded series is NOT donated — no output shares
#: its [bucket, T] shape, so XLA could never alias it (donating it just
#: trips the unusable-donation warning) — and the shared device-cache
#: fleet arrays are deliberately not donated anywhere: concurrent
#: requests read them (ADR-020).
_bucketed_warm_fit_forecast_program = jax.jit(
    _bucketed_fit_body,
    static_argnames=("cfg", "steps", "inference", "batch_p"),
    donate_argnums=(2, 3),
)


def _bucketed_cold_fit_body(
    series: jax.Array,
    chip_weights: jax.Array,
    key: jax.Array,
    cfg: ForecastConfig,
    steps: int,
    inference: str,
    batch_p: int,
) -> tuple[jax.Array, Params, Any, jax.Array]:
    """Masked twin of :func:`_fit_forecast_state_program`: fresh init →
    the SAME weighted scan body — cold and warm bucketed fits cannot
    train different models."""
    params = init_params(key, cfg)
    opt_state = optax.adam(cfg.learning_rate).init(params)
    return _bucketed_fit_body(
        series, chip_weights, params, opt_state, cfg, steps, inference, batch_p
    )


_bucketed_fit_forecast_state_program = jax.jit(
    _bucketed_cold_fit_body,
    static_argnames=("cfg", "steps", "inference", "batch_p"),
)


def _rollup_forecast_body(
    node_capacity: jax.Array,
    node_allocatable: jax.Array,
    node_ready: jax.Array,
    node_generation: jax.Array,
    node_valid: jax.Array,
    pod_request: jax.Array,
    pod_phase: jax.Array,
    pod_node_idx: jax.Array,
    pod_valid: jax.Array,
    series: jax.Array,
    chip_weights: jax.Array,
    params: Params,
    opt_state: Any,
    cfg: ForecastConfig,
    steps: int,
    inference: str,
    batch_p: int,
) -> tuple[dict[str, jax.Array], jax.Array, Params, Any, jax.Array]:
    """THE fused request path (ADR-020): fleet rollup + warm forecast
    refinement + inference as ONE XLA program and ONE dispatch. The
    fleet columns arrive straight from the ADR-012 device cache, so
    nothing round-trips host↔device between the stages; the caller
    fetches (rollup, predictions, mse) through the transfer funnel in
    one coalesced device_get."""
    from ..analytics.fleet_jax import fleet_rollup  # lazy: import cycle

    rollup = fleet_rollup(
        node_capacity, node_allocatable, node_ready, node_generation,
        node_valid, pod_request, pod_phase, pod_node_idx, pod_valid,
    )
    out, params, opt_state, mse = _bucketed_fit_body(
        series, chip_weights, params, opt_state, cfg, steps, inference, batch_p
    )
    return rollup, out, params, opt_state, mse


#: Donates the params/opt_state carry (11, 12) — the request-private,
#: output-aliasable inputs. The padded series (9) is skipped for the
#: same no-matching-output-shape reason as the warm program, and the
#: nine fleet columns (0-8) are the shared device-cache entry and MUST
#: survive the call (see ADR-020 for why the ISSUE's "donate fleet
#: buffers" is deliberately narrowed).
rollup_and_forecast_program = jax.jit(
    _rollup_forecast_body,
    static_argnames=("cfg", "steps", "inference", "batch_p"),
    donate_argnums=(11, 12),
)


def pad_series_to_bucket(
    series: jax.Array, bucket: int
) -> tuple[jax.Array, jax.Array]:
    """(padded [bucket, T] series, [bucket] float32 weights): zero rows
    beyond the real chip count with weight 0.0, so the masked programs
    train on exactly the real chips; callers slice predictions back to
    ``series.shape[0]`` rows."""
    n_chips = series.shape[0]
    padded = (
        jnp.zeros((bucket, series.shape[1]), jnp.float32)
        .at[:n_chips]
        .set(series.astype(jnp.float32))
    )
    weights = jnp.zeros((bucket,), jnp.float32).at[:n_chips].set(1.0)
    return padded, weights


#: Plain jitted program → AOT registry name for its bucketed twin.
_AOT_FORECAST_NAMES = {
    "_warm_fit_forecast_program": "forecast.aot_warm_fit_forecast",
    "_fit_forecast_state_program": "forecast.aot_fit_forecast_state",
}


def _try_aot_forecast(
    program: Callable[..., Any], head: tuple[Any, ...],
    inference: str, batch_p: int,
) -> tuple[jax.Array, Params, Any, jax.Array] | None:
    """Serve a fused fit+forecast from a registry-precompiled bucketed
    executable (ADR-020). Returns the plain program's result tuple with
    predictions sliced back to the real chip count, or ``None`` when no
    precompiled executable covers the call — registry absent or still
    compiling, chip count above every bucket, or unregistered statics —
    in which case the caller's plain jitted path runs (the ledger then
    counts its compile as request-phase; a miss is never an error).

    The ledger signature here is EXACTLY the key the registry's startup
    thread tracked with ``phase="startup"``, so the request-side call
    classifies as a warm dispatch and the post-warmup request-compile
    count stays zero."""
    kind = _AOT_FORECAST_NAMES.get(getattr(program, "__name__", ""))
    if kind is None:
        return None
    from . import aot

    reg = aot.registry()
    if reg is None or not reg.ready():
        return None
    series = head[0]
    cfg, steps = head[-2], head[-1]
    n_chips, length = series.shape
    bucket = aot.chip_bucket_for(n_chips)
    if bucket is None:
        # Above the top bucket: a counted miss, never an error.
        reg.note_bucket_miss(kind)
        return None
    sig = (bucket, length, cfg, steps, inference, batch_p)
    exe = reg.executable(kind, sig)
    if exe is None:
        return None
    padded, weights = pad_series_to_bucket(series, bucket)
    donated = 0
    if kind == "forecast.aot_warm_fit_forecast":
        # params + opt_state buffers the donation lets XLA reuse in
        # place (the registry's savings counter).
        donated = sum(
            int(leaf.nbytes)
            for leaf in jax.tree_util.tree_leaves(head[1:3])
        )
    try:
        with _jax_track(kind, sig):
            out, params, opt_state, mse = exe(padded, weights, *head[1:-2])
    except Exception as exc:  # noqa: BLE001 — AOT is an optimization
        reg.note_exec_failure(kind, f"{type(exc).__name__}: {exc}"[:200])
        return None
    if donated:
        reg.note_donation(donated)
    return out[:n_chips], params, opt_state, mse
