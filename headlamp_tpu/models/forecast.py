"""Utilization forecaster: windows of chip telemetry → near-future load.

Architecture notes (TPU-first):
- Three dense layers; matmuls run in **bfloat16** with float32
  accumulation/params — the MXU-native precision recipe.
- Static shapes everywhere; the whole train step jits to one program.
- Sharding: batch over the ``data`` mesh axis, hidden features over
  ``model`` (see :func:`param_shardings`); XLA/GSPMD inserts the
  collectives (all-reduce of activations/grads) from the annotations
  alone — no hand-written collectives in the model.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Params = dict[str, jax.Array]


@dataclass(frozen=True)
class ForecastConfig:
    window: int = 32      #: history samples per example
    hidden: int = 128     #: hidden width (MXU-friendly multiple of 128)
    horizon: int = 8      #: future samples predicted
    learning_rate: float = 1e-3


def init_params(key: jax.Array, cfg: ForecastConfig) -> Params:
    k1, k2, k3 = jax.random.split(key, 3)

    def glorot(k: jax.Array, shape: tuple[int, int]) -> jax.Array:
        scale = jnp.sqrt(2.0 / (shape[0] + shape[1]))
        return jax.random.normal(k, shape, dtype=jnp.float32) * scale

    return {
        "w1": glorot(k1, (cfg.window, cfg.hidden)),
        "b1": jnp.zeros((cfg.hidden,), jnp.float32),
        "w2": glorot(k2, (cfg.hidden, cfg.hidden)),
        "b2": jnp.zeros((cfg.hidden,), jnp.float32),
        "w3": glorot(k3, (cfg.hidden, cfg.horizon)),
        "b3": jnp.zeros((cfg.horizon,), jnp.float32),
    }


def _dense_bf16(x: jax.Array, w: jax.Array, b: jax.Array) -> jax.Array:
    """bf16 matmul, f32 accumulate+bias — the MXU precision pattern."""
    y = jax.lax.dot_general(
        x.astype(jnp.bfloat16),
        w.astype(jnp.bfloat16),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return y + b


def forward(params: Params, x: jax.Array) -> jax.Array:
    """[batch, window] -> [batch, horizon] utilization fractions.
    Output squashed to [0, 1] — utilization can't leave that range."""
    h = jax.nn.gelu(_dense_bf16(x, params["w1"], params["b1"]))
    h = jax.nn.gelu(_dense_bf16(h, params["w2"], params["b2"]))
    return jax.nn.sigmoid(_dense_bf16(h, params["w3"], params["b3"]))


def loss_fn(params: Params, x: jax.Array, y: jax.Array) -> jax.Array:
    pred = forward(params, x)
    return jnp.mean((pred - y) ** 2)


def make_train_step(
    cfg: ForecastConfig,
) -> tuple[Callable[..., Any], optax.GradientTransformation]:
    """(jitted train_step, optimizer). ``train_step(params, opt_state,
    x, y) -> (params, opt_state, loss)`` — one fused XLA program."""
    optimizer = optax.adam(cfg.learning_rate)

    @jax.jit
    def train_step(
        params: Params, opt_state: Any, x: jax.Array, y: jax.Array
    ) -> tuple[Params, Any, jax.Array]:
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step, optimizer


def param_shardings(mesh: Mesh) -> dict[str, NamedSharding]:
    """dp×tp layout: w1 columns / w2 rows over ``model`` (megatron-style
    pairing keeps the activation all-reduce to one per block); the output
    projection replicated (horizon is tiny)."""
    s = lambda *spec: NamedSharding(mesh, P(*spec))  # noqa: E731
    return {
        "w1": s(None, "model"),
        "b1": s("model"),
        "w2": s("model", None),
        "b2": s(None),
        "w3": s(None),
        "b3": s(None),
    }


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("data", None))


# ---------------------------------------------------------------------------
# Synthetic telemetry (deterministic; demos/tests/benches)
# ---------------------------------------------------------------------------

def synthetic_telemetry(
    n_series: int, length: int, key: jax.Array | None = None
) -> jax.Array:
    """[n_series, length] utilization traces: per-chip base load + two
    harmonics + noise, clipped to [0,1]. Deterministic under a fixed
    key so fixtures and benches reproduce."""
    key = key if key is not None else jax.random.PRNGKey(20260729)
    k_base, k_phase, k_noise = jax.random.split(key, 3)
    t = jnp.arange(length, dtype=jnp.float32)
    base = jax.random.uniform(k_base, (n_series, 1), minval=0.25, maxval=0.7)
    phase = jax.random.uniform(k_phase, (n_series, 2), maxval=2 * jnp.pi)
    wave = 0.18 * jnp.sin(t[None, :] / 17.0 + phase[:, :1]) + 0.09 * jnp.sin(
        t[None, :] / 5.0 + phase[:, 1:]
    )
    noise = 0.04 * jax.random.normal(k_noise, (n_series, length))
    return jnp.clip(base + wave + noise, 0.0, 1.0)


def make_windows(
    series: jax.Array, window: int, horizon: int
) -> tuple[jax.Array, jax.Array]:
    """Sliding (x, y) examples from [n_series, length] traces, flattened
    across series. Static-shape unfold via gather indices (no Python
    loop over positions)."""
    n_series, length = series.shape
    n_pos = length - window - horizon + 1
    if n_pos <= 0:
        raise ValueError("series shorter than window + horizon")
    starts = jnp.arange(n_pos)
    x_idx = starts[:, None] + jnp.arange(window)[None, :]
    y_idx = starts[:, None] + window + jnp.arange(horizon)[None, :]
    x = series[:, x_idx].reshape(n_series * n_pos, window)
    y = series[:, y_idx].reshape(n_series * n_pos, horizon)
    return x, y


class InferenceDispatch(NamedTuple):
    """Which inference path actually served a forecast — observability
    for the silent-fallback policy (a Pallas kernel broken by a jax
    upgrade must show up in /healthz-adjacent surfaces and the bench,
    not vanish behind the XLA fallback)."""

    path: str                        #: "pallas" | "xla" | "repeat"
    fallback_reason: str | None = None  #: set when Pallas was tried and failed
    #: Final training MSE of the fit, as a DEVICE scalar (None on the
    #: persistence path) — callers materialize it together with the
    #: predictions in one device_get; a separate float() would cost an
    #: extra round-trip over a tunneled chip.
    fit_mse: Any = None

    @property
    def used_pallas(self) -> bool:
        return self.path == "pallas"


def forecast_next_with_dispatch(
    params: Params, recent: jax.Array, cfg: ForecastConfig | None = None
) -> tuple[jax.Array, InferenceDispatch]:
    """Pages' inference entry: [n_chips, window] recent samples ->
    ([n_chips, horizon] predicted utilization, dispatch record).

    Dispatch: on a TPU backend the fused Pallas kernel serves inference
    (``pallas_forward.forecast_forward_pallas`` — every intermediate
    stays in VMEM); elsewhere the plain XLA ``forward``. Any Pallas
    failure falls back to XLA — the kernel is an optimization, never a
    dependency — but the failure is RECORDED in the returned dispatch,
    never swallowed invisibly."""
    if jax.devices()[0].platform == "tpu":
        try:
            from .pallas_forward import forecast_forward_pallas

            out = forecast_forward_pallas(params, recent, cfg, interpret=False)
            return out, InferenceDispatch("pallas")
        except Exception as exc:  # noqa: BLE001 — optimization, not a dependency
            reason = f"{type(exc).__name__}: {exc}"[:200]
            return forward(params, recent), InferenceDispatch("xla", reason)
    return forward(params, recent), InferenceDispatch("xla")


def forecast_next(
    params: Params, recent: jax.Array, cfg: ForecastConfig | None = None
) -> jax.Array:
    """:func:`forecast_next_with_dispatch` without the record, for
    callers that only want the numbers."""
    out, _ = forecast_next_with_dispatch(params, recent, cfg)
    return out


@partial(jax.jit, static_argnames=("cfg", "steps"))
def _fit_program(
    series: jax.Array,
    key: jax.Array,
    cfg: ForecastConfig,
    steps: int,
) -> tuple[Params, jax.Array]:
    """windowing → init → ``steps`` optimizer steps (lax.scan) →
    (fitted params, final training MSE), as ONE XLA program. A Python
    training loop would issue one device dispatch per step — tens of
    round-trips on a remote/tunneled TPU for a fit the fused program
    finishes in a single dispatch; the windowing (``make_windows``'s
    gathers) is fused in too, because each un-jitted jnp op is its own
    dispatch and over a tunneled chip those round-trips dominate the
    whole fit. The final MSE travels with the params so surfacing fit
    quality costs no extra dispatch."""
    x, y = make_windows(series, cfg.window, cfg.horizon)
    params = init_params(key, cfg)
    optimizer = optax.adam(cfg.learning_rate)
    opt_state = optimizer.init(params)

    def body(
        carry: tuple[Params, Any], _: None
    ) -> tuple[tuple[Params, Any], jax.Array]:
        p, s = carry
        loss, grads = jax.value_and_grad(loss_fn)(p, x, y)
        updates, s = optimizer.update(grads, s, p)
        p = optax.apply_updates(p, updates)
        return (p, s), loss

    (params, _), _ = jax.lax.scan(body, (params, opt_state), None, length=steps)
    # Self-assessment of the RETURNED model: scan losses are computed
    # before each update, so losses[-1] would describe the penultimate
    # params. One more loss_fn at the final params stays in the fused
    # program — negligible next to the scan.
    return params, loss_fn(params, x, y)


@partial(jax.jit, static_argnames=("cfg", "steps", "inference", "batch_p"))
def _fit_forecast_program(
    series: jax.Array,
    key: jax.Array,
    cfg: ForecastConfig,
    steps: int,
    inference: str,
    batch_p: int,
) -> tuple[jax.Array, jax.Array]:
    """The WHOLE forecast — windowing → fit scan → inference (Pallas
    kernel or XLA forward, chosen statically) — as ONE XLA program and
    therefore ONE device dispatch. The split fit/infer path costs two
    dispatches; over a tunneled/remote TPU each round-trip is ~50-70 ms
    (BENCH_r03 measured the rollup's dispatch at ~150 ms end-to-end),
    so fusing the pair nearly halves the serving-path forecast cost.

    The fit is :func:`_fit_program` itself — nested jit inlines into the
    enclosing trace, so the serving path and the standalone fit (which
    the bench's parity check uses) can never train different models."""
    params, final_loss = _fit_program(series, key, cfg, steps)
    recent = series[:, -cfg.window:]
    if inference == "pallas":
        from .pallas_forward import forecast_forward_padded

        out = forecast_forward_padded(
            params, recent, batch_p=batch_p, horizon=cfg.horizon, interpret=False
        )
    else:
        out = forward(params, recent)
    return out, final_loss


def fit_and_forecast_with_dispatch(
    series: jax.Array,
    cfg: ForecastConfig | None = None,
    *,
    steps: int = 60,
    seed: int = 0,
) -> tuple[jax.Array, InferenceDispatch]:
    """Online fit on the given traces, then predict the next horizon
    from each trace's latest window: [n_chips, T] -> ([n_chips, horizon],
    dispatch record). Fit AND inference run as one fused program
    (:func:`_fit_forecast_program`) — the Pallas kernel inlined on a TPU
    backend, plain XLA elsewhere; any Pallas failure falls back to the
    fused XLA variant with the reason recorded.

    There is no pre-trained checkpoint by design — utilization dynamics
    are cluster-specific, the model is tiny, and fitting on exactly the
    window the page displays keeps the prediction honest. Traces shorter
    than window+horizon fall back to persistence (repeat last value)."""
    cfg = cfg or ForecastConfig()
    series = jnp.asarray(series, dtype=jnp.float32)
    n_chips, length = series.shape
    if length < cfg.window + cfg.horizon:
        # Persistence fallback: no kernel ran at all, and the dispatch
        # record must say so — not claim an XLA inference that never
        # happened.
        last = series[:, -1:]
        return jnp.repeat(last, cfg.horizon, axis=1), InferenceDispatch("repeat")

    key = jax.random.PRNGKey(seed)
    if jax.devices()[0].platform == "tpu" and _pallas_broken_reason is None:
        try:
            from .pallas_forward import check_single_tile, pallas_batch_p

            check_single_tile(cfg.window, cfg.hidden, cfg.horizon)
            out, mse = _fit_forecast_program(
                series, key, cfg, steps, "pallas", pallas_batch_p(n_chips)
            )
            return out, InferenceDispatch("pallas", fit_mse=mse)
        except Exception as exc:  # noqa: BLE001 — optimization, not a dependency
            # Memoize: a kernel that failed to lower/compile would
            # otherwise re-pay the failed compile on EVERY forecast.
            _record_pallas_broken(f"{type(exc).__name__}: {exc}"[:200])
    out, mse = _fit_forecast_program(series, key, cfg, steps, "xla", 0)
    return out, InferenceDispatch("xla", _pallas_broken_reason, fit_mse=mse)


#: Once the fused Pallas variant fails, the reason is memoized and every
#: later forecast serves the fused-XLA variant immediately — recorded in
#: each dispatch (and thus the page + bench), reset only per process.
_pallas_broken_reason: str | None = None


def _record_pallas_broken(reason: str) -> None:
    global _pallas_broken_reason
    _pallas_broken_reason = reason


def fit_and_forecast(
    series: jax.Array,
    cfg: ForecastConfig | None = None,
    *,
    steps: int = 60,
    seed: int = 0,
) -> jax.Array:
    """:func:`fit_and_forecast_with_dispatch` without the record."""
    out, _ = fit_and_forecast_with_dispatch(series, cfg, steps=steps, seed=seed)
    return out
