"""Fleet fixture generators for the BASELINE configs.

The reference proves "multi-node" behaviour purely with fixture objects
(SURVEY.md §4); this module is the factory for the TPU equivalents:

- ``fleet_v5e4``   — GKE v5e-4 single-host node pool (BASELINE config #2)
- ``fleet_v5p32``  — v5p-32 multi-host pod slice: 16 chips over 4 hosts
                     (config #3)
- ``fleet_mixed``  — Intel Arc dGPU nodes + v5e nodes (config #4)
- ``fleet_large``  — deterministic 1024-node stress fleet (config #5)

All generators are deterministic (seeded, fixed clock) so the same JSON
snapshots can be shared with the TS vitest suites (fixtures/*.json).
"""

from __future__ import annotations

import copy
import random
from typing import TYPE_CHECKING, Any

if TYPE_CHECKING:  # runtime import stays local to fleet_transport
    from ..transport.api_proxy import MockTransport

from ..domain.constants import (
    GKE_NODEPOOL_LABEL,
    GKE_TPU_ACCELERATOR_LABEL,
    GKE_TPU_TOPOLOGY_LABEL,
    GKE_TPU_WORKER_ID_LABEL,
    HEADLAMP_CLUSTER_LABEL,
    TPU_PLUGIN_NAMESPACE,
    TPU_RESOURCE,
)

#: Fixed "now" for deterministic ages: 2026-07-29T00:00:00Z.
FIXTURE_NOW_EPOCH = 1785283200.0
FIXTURE_NOW_ISO = "2026-07-29T00:00:00Z"


def _ts(age_seconds: int) -> str:
    import datetime

    dt = datetime.datetime.fromtimestamp(
        FIXTURE_NOW_EPOCH - age_seconds, tz=datetime.timezone.utc
    )
    return dt.strftime("%Y-%m-%dT%H:%M:%SZ")


# ---------------------------------------------------------------------------
# Object builders
# ---------------------------------------------------------------------------

def make_tpu_node(
    name: str,
    *,
    pool: str | None = None,
    accelerator: str = "tpu-v5-lite-podslice",
    topology: str | None = "2x2",
    chips: int = 4,
    ready: bool = True,
    worker_id: int | None = None,
    age_seconds: int = 3600 * 24,
    uid: str | None = None,
    cluster: str | None = None,
) -> dict[str, Any]:
    labels: dict[str, str] = {GKE_TPU_ACCELERATOR_LABEL: accelerator}
    if topology:
        labels[GKE_TPU_TOPOLOGY_LABEL] = topology
    if pool:
        labels[GKE_NODEPOOL_LABEL] = pool
    if worker_id is not None:
        labels[GKE_TPU_WORKER_ID_LABEL] = str(worker_id)
    if cluster is not None:
        labels[HEADLAMP_CLUSTER_LABEL] = cluster
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {
            "name": name,
            "uid": uid or f"uid-node-{name}",
            "labels": labels,
            "creationTimestamp": _ts(age_seconds),
        },
        "status": {
            "capacity": {"cpu": "96", "memory": "407Gi", TPU_RESOURCE: str(chips)},
            "allocatable": {"cpu": "95", "memory": "400Gi", TPU_RESOURCE: str(chips)},
            "conditions": [{"type": "Ready", "status": "True" if ready else "False"}],
            "nodeInfo": {
                "osImage": "Container-Optimized OS from Google",
                "kernelVersion": "6.1.0-gke",
                "kubeletVersion": "v1.30.2-gke",
                "architecture": "amd64",
            },
        },
    }


def make_plain_node(name: str, *, age_seconds: int = 3600 * 24) -> dict[str, Any]:
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {
            "name": name,
            "uid": f"uid-node-{name}",
            "labels": {},
            "creationTimestamp": _ts(age_seconds),
        },
        "status": {
            "capacity": {"cpu": "8", "memory": "32Gi"},
            "allocatable": {"cpu": "8", "memory": "31Gi"},
            "conditions": [{"type": "Ready", "status": "True"}],
        },
    }


def make_intel_node(
    name: str,
    *,
    gpus: int = 1,
    discrete: bool = True,
    ready: bool = True,
    age_seconds: int = 3600 * 24,
) -> dict[str, Any]:
    labels = {"intel.feature.node.kubernetes.io/gpu": "true"}
    if discrete:
        labels["node-role.kubernetes.io/gpu"] = "true"
    else:
        labels["node-role.kubernetes.io/igpu"] = "true"
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {
            "name": name,
            "uid": f"uid-node-{name}",
            "labels": labels,
            "creationTimestamp": _ts(age_seconds),
        },
        "status": {
            "capacity": {"cpu": "16", "memory": "64Gi", "gpu.intel.com/i915": str(gpus)},
            "allocatable": {"cpu": "16", "memory": "62Gi", "gpu.intel.com/i915": str(gpus)},
            "conditions": [{"type": "Ready", "status": "True" if ready else "False"}],
        },
    }


def make_tpu_pod(
    name: str,
    *,
    namespace: str = "default",
    node: str | None = None,
    chips: int = 4,
    phase: str = "Running",
    ready: bool | None = None,
    restarts: int = 0,
    age_seconds: int = 3600,
    waiting_reason: str | None = None,
) -> dict[str, Any]:
    if ready is None:
        ready = phase == "Running"
    state: dict[str, Any] = {}
    if waiting_reason:
        state = {"waiting": {"reason": waiting_reason}}
    elif phase == "Running":
        state = {"running": {"startedAt": _ts(age_seconds)}}
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "uid": f"uid-pod-{namespace}-{name}",
            "labels": {"app": "training"},
            "creationTimestamp": _ts(age_seconds),
        },
        "spec": {
            "nodeName": node,
            "containers": [
                {
                    "name": "worker",
                    "image": "example/jax-train:latest",
                    "resources": {
                        "requests": {TPU_RESOURCE: str(chips)},
                        "limits": {TPU_RESOURCE: str(chips)},
                    },
                }
            ],
        },
        "status": {
            "phase": phase,
            "conditions": [{"type": "Ready", "status": "True" if ready else "False"}],
            "containerStatuses": [
                {
                    "name": "worker",
                    "ready": ready,
                    "restartCount": restarts,
                    **({"state": state} if state else {}),
                }
            ],
        },
    }


def make_intel_pod(
    name: str,
    *,
    namespace: str = "default",
    node: str | None = None,
    gpus: int = 1,
    phase: str = "Running",
    age_seconds: int = 3600,
) -> dict[str, Any]:
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "uid": f"uid-pod-{namespace}-{name}",
            "creationTimestamp": _ts(age_seconds),
        },
        "spec": {
            "nodeName": node,
            "containers": [
                {
                    "name": "app",
                    "resources": {
                        "requests": {"gpu.intel.com/i915": str(gpus)},
                        "limits": {"gpu.intel.com/i915": str(gpus)},
                    },
                }
            ],
        },
        "status": {
            "phase": phase,
            "conditions": [{"type": "Ready", "status": "True" if phase == "Running" else "False"}],
            "containerStatuses": [{"name": "app", "ready": phase == "Running", "restartCount": 0}],
        },
    }


def make_plugin_pod(
    name: str,
    *,
    provider: str = "tpu",
    node: str | None = None,
    ready: bool = True,
    restarts: int = 0,
    age_seconds: int = 3600 * 48,
) -> dict[str, Any]:
    if provider == "tpu":
        labels = {"k8s-app": "tpu-device-plugin"}
        namespace = TPU_PLUGIN_NAMESPACE
    else:
        labels = {"app": "intel-gpu-plugin"}
        namespace = "inteldeviceplugins-system"
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "uid": f"uid-pod-{namespace}-{name}",
            "labels": labels,
            "creationTimestamp": _ts(age_seconds),
        },
        "spec": {"nodeName": node, "containers": [{"name": "device-plugin"}]},
        "status": {
            "phase": "Running",
            "conditions": [{"type": "Ready", "status": "True" if ready else "False"}],
            "containerStatuses": [
                {"name": "device-plugin", "ready": ready, "restartCount": restarts}
            ],
        },
    }


def make_plugin_daemonset(
    *, desired: int = 1, ready: int | None = None, unavailable: int = 0
) -> dict[str, Any]:
    if ready is None:
        ready = desired
    return {
        "apiVersion": "apps/v1",
        "kind": "DaemonSet",
        "metadata": {
            "name": "tpu-device-plugin",
            "namespace": TPU_PLUGIN_NAMESPACE,
            "uid": "uid-ds-tpu-device-plugin",
            "creationTimestamp": _ts(3600 * 72),
        },
        "status": {
            "desiredNumberScheduled": desired,
            "numberReady": ready,
            "numberUnavailable": unavailable,
            "numberAvailable": ready,
        },
    }


def make_intel_crd(
    name: str = "gpudeviceplugin-sample",
    *,
    desired: int = 1,
    ready: int | None = None,
    shared_dev_num: int = 1,
    age_seconds: int = 3600 * 72,
) -> dict[str, Any]:
    """A GpuDevicePlugin CR shaped like the reference's domain model
    (`/root/reference/src/api/k8s.ts:56-80`)."""
    if ready is None:
        ready = desired
    return {
        "apiVersion": "deviceplugin.intel.com/v1",
        "kind": "GpuDevicePlugin",
        "metadata": {
            "name": name,
            "uid": f"uid-crd-{name}",
            "creationTimestamp": _ts(age_seconds),
        },
        "spec": {
            "image": "intel/intel-gpu-plugin:0.30.0",
            "sharedDevNum": shared_dev_num,
            "preferredAllocationPolicy": "balanced",
            "enableMonitoring": True,
            "nodeSelector": {"intel.feature.node.kubernetes.io/gpu": "true"},
        },
        "status": {
            "desiredNumberScheduled": desired,
            "numberReady": ready,
        },
    }


def fleet_transport(fleet: dict[str, Any]) -> "MockTransport":
    """MockTransport serving a fixture fleet on the same URL surface the
    context fetches (single definition — the server demo mode and
    bench.py must wire identical routes, or a drifted daemonset path
    would silently bench the degraded render path)."""
    from ..transport.api_proxy import MockTransport

    t = MockTransport()
    # Watchable lists serve limit/continue pagination like the apiserver
    # plus the watch-delta protocol — the context always pages its
    # reactive lists and, with watch enabled, polls deltas, so the
    # fixture transport must speak both. The feeds are exposed on the
    # transport (``t.node_feed`` / ``t.pod_feed``) for scenario tests
    # that mutate the fleet mid-run.
    t.node_feed = t.add_watchable_list("/api/v1/nodes", fleet["nodes"])
    t.pod_feed = t.add_watchable_list("/api/v1/pods", fleet["pods"])
    t.add(
        "/apis/apps/v1/daemonsets?labelSelector=k8s-app%3Dtpu-device-plugin",
        {"kind": "List", "items": fleet.get("daemonsets", [])},
    )
    if "gpudeviceplugins" in fleet:
        t.add(
            "/apis/deviceplugin.intel.com/v1/gpudeviceplugins",
            {"kind": "List", "items": fleet["gpudeviceplugins"]},
        )
    return t


# ---------------------------------------------------------------------------
# BASELINE config fleets
# ---------------------------------------------------------------------------

def fleet_v5e4() -> dict[str, Any]:
    """Config #2: one v5e-4 single-host node (2x2 topology, 4 chips)."""
    node = make_tpu_node(
        "gke-tpu-v5e-pool-a1b2", pool="v5e-pool",
        accelerator="tpu-v5-lite-podslice", topology="2x2", chips=4,
    )
    pods = [
        make_tpu_pod("train-step-0", node=node["metadata"]["name"], chips=4),
        make_tpu_pod("eval-job", node=None, chips=4, phase="Pending",
                     waiting_reason="Unschedulable"),
    ]
    plugin = make_plugin_pod("tpu-device-plugin-x1", node=node["metadata"]["name"])
    return {
        "nodes": [node, make_plain_node("gke-default-pool-c3d4")],
        "pods": pods + [plugin],
        "daemonsets": [make_plugin_daemonset(desired=1)],
    }


def fleet_v5p32() -> dict[str, Any]:
    """Config #3: v5p-32 multi-host pod slice — 16 chips (32 TensorCores)
    over 4 hosts of 4 chips, 2x2x4 topology."""
    nodes = [
        make_tpu_node(
            f"gke-v5p-pool-w{i}", pool="v5p-pool",
            accelerator="tpu-v5p-slice", topology="2x2x4", chips=4,
            worker_id=i, ready=(i != 3),
        )
        for i in range(4)
    ]
    pods = [
        make_tpu_pod(f"megatrain-{i}", namespace="ml", node=nodes[i]["metadata"]["name"], chips=4)
        for i in range(3)
    ]
    plugins = [
        make_plugin_pod(f"tpu-device-plugin-{i}", node=nodes[i]["metadata"]["name"])
        for i in range(4)
    ]
    return {
        "nodes": nodes + [make_plain_node("gke-default-pool-e5f6")],
        "pods": pods + plugins,
        "daemonsets": [make_plugin_daemonset(desired=4)],
    }


def fleet_v5p32_degraded() -> dict[str, Any]:
    """The v5p-32 slice after a host drop: worker 3 gone entirely and
    worker 2 NotReady — the degraded-fleet shape every surface must
    classify the same way (slice health 'error': an incomplete
    multi-host slice outranks mere unreadiness, topology/slices.py).
    Exported as the `v5p32-degraded` shared fixture and driven by
    dryrun_multichip stage 6."""
    fleet = copy.deepcopy(fleet_v5p32())
    fleet["nodes"] = [
        n for n in fleet["nodes"] if n["metadata"]["name"] != "gke-v5p-pool-w3"
    ]
    for n in fleet["nodes"]:
        if n["metadata"]["name"] == "gke-v5p-pool-w2":
            for c in n.get("status", {}).get("conditions", []):
                if c.get("type") == "Ready":
                    c["status"] = "False"
    return fleet


def fleet_mixed() -> dict[str, Any]:
    """Config #4: Intel Arc dGPU nodes + v5e nodes in one cluster."""
    tpu_nodes = [
        make_tpu_node(
            f"gke-v5e16-pool-w{i}", pool="v5e16-pool",
            accelerator="tpu-v5-lite-podslice", topology="4x4", chips=4,
        )
        for i in range(4)
    ]
    intel_nodes = [
        make_intel_node("arc-node-1", gpus=2),
        make_intel_node("arc-node-2", gpus=1, discrete=True, ready=False),
    ]
    pods = [
        make_tpu_pod("llm-shard-0", namespace="ml", node=tpu_nodes[0]["metadata"]["name"], chips=4),
        make_tpu_pod("llm-shard-1", namespace="ml", node=tpu_nodes[1]["metadata"]["name"], chips=4),
        make_intel_pod("transcode-1", node="arc-node-1", gpus=1),
        make_intel_pod("transcode-2", node="arc-node-1", gpus=1, phase="Pending"),
    ]
    plugins = [
        make_plugin_pod("tpu-device-plugin-a", node=tpu_nodes[0]["metadata"]["name"]),
        make_plugin_pod("intel-gpu-plugin-a", provider="intel", node="arc-node-1"),
    ]
    return {
        "nodes": tpu_nodes + intel_nodes + [make_plain_node("gke-default-pool-m1")],
        "pods": pods + plugins,
        "daemonsets": [make_plugin_daemonset(desired=4)],
        "gpudeviceplugins": [make_intel_crd(desired=2)],
    }


def fleet_large(n_nodes: int = 1024, seed: int = 42) -> dict[str, Any]:
    """Config #5: deterministic stress fleet. ~1/8 plain nodes; the rest
    TPU hosts spread over multi-host v5e-16 / v5p pools plus single-host
    v5e and v6e pools, with a pod population exercising every phase."""
    rng = random.Random(seed)
    nodes: list[dict[str, Any]] = []
    pods: list[dict[str, Any]] = []

    pool_idx = 0
    while len(nodes) < n_nodes:
        remaining = n_nodes - len(nodes)
        kind = rng.random()
        if remaining >= 8 and kind < 0.35:
            # v5e-16 multi-host pool: 4 hosts x 4 chips.
            pool = f"v5e16-pool-{pool_idx}"
            for w in range(4):
                nodes.append(
                    make_tpu_node(
                        f"gke-{pool}-w{w}", pool=pool,
                        accelerator="tpu-v5-lite-podslice", topology="4x4",
                        chips=4, worker_id=w,
                        ready=rng.random() > 0.03,
                        age_seconds=rng.randrange(3600, 3600 * 24 * 30),
                    )
                )
        elif remaining >= 8 and kind < 0.55:
            # v5p pool: 8 hosts x 4 chips, 2x4x4 topology.
            pool = f"v5p-pool-{pool_idx}"
            for w in range(8):
                nodes.append(
                    make_tpu_node(
                        f"gke-{pool}-w{w}", pool=pool,
                        accelerator="tpu-v5p-slice", topology="2x4x4",
                        chips=4, worker_id=w,
                        ready=rng.random() > 0.03,
                        age_seconds=rng.randrange(3600, 3600 * 24 * 30),
                    )
                )
        elif kind < 0.85:
            # Single-host v5e / v6e node. Chips follow the topology — a
            # "2x4" single host carries exactly 8 chips on GKE; drawing
            # them independently would fabricate impossible slices.
            accel = "tpu-v6e-slice" if rng.random() < 0.4 else "tpu-v5-lite-podslice"
            pool = f"single-pool-{pool_idx}"
            topology = rng.choice(["2x2", "2x4", "1x1"])
            chips = {"1x1": 1, "2x2": 4, "2x4": 8}[topology]
            nodes.append(
                make_tpu_node(
                    f"gke-{pool}-x0", pool=pool, accelerator=accel,
                    topology=topology, chips=chips,
                    ready=rng.random() > 0.02,
                    age_seconds=rng.randrange(3600, 3600 * 24 * 30),
                )
            )
        else:
            nodes.append(make_plain_node(f"gke-cpu-pool-n{pool_idx}"))
        pool_idx += 1

    nodes = nodes[:n_nodes]
    tpu_node_names = [
        n["metadata"]["name"]
        for n in nodes
        if GKE_TPU_ACCELERATOR_LABEL in n["metadata"]["labels"]
    ]

    phases = ["Running"] * 7 + ["Pending", "Succeeded", "Failed"]
    for i, node_name in enumerate(tpu_node_names):
        if rng.random() < 0.7:
            phase = rng.choice(phases)
            pods.append(
                make_tpu_pod(
                    f"workload-{i}", namespace=f"team-{i % 7}",
                    node=node_name if phase != "Pending" else None,
                    chips=rng.choice([1, 4, 4, 8]),
                    phase=phase,
                    restarts=rng.choice([0, 0, 0, 1, 3]),
                    age_seconds=rng.randrange(60, 3600 * 24 * 7),
                    waiting_reason="Unschedulable" if phase == "Pending" else None,
                )
            )
        if rng.random() < 0.995:
            pods.append(make_plugin_pod(f"tpu-device-plugin-{i}", node=node_name))

    return {
        "nodes": nodes,
        "pods": pods,
        "daemonsets": [make_plugin_daemonset(desired=len(tpu_node_names))],
    }


def fleet_viewport(
    n_nodes: int = 16384, seed: int = 7, clusters: int = 8
) -> dict[str, Any]:
    """Config #6: the ADR-026 drill-down fleet. Every node is a TPU
    host stamped with a :data:`HEADLAMP_CLUSTER_LABEL` value and a node
    pool, so the viewport tree has real structure at every level:
    ``clusters`` clusters × ~32-host slices × 4 chips. Pod count stays
    ≤ node count (one workload per ~2 nodes) so the encoder's
    power-of-two buckets come out SQUARE — (1024,1024), (4096,4096),
    (16384,16384) — exactly the shapes the AOT bucket table and
    ``bench_viewport`` pin. Deterministic like every generator here."""
    rng = random.Random(seed)
    nodes: list[dict[str, Any]] = []
    pods: list[dict[str, Any]] = []
    slice_hosts = 32

    i = 0
    while len(nodes) < n_nodes:
        cluster = str(i % clusters)
        pool = f"c{cluster}-slice-{i // clusters}"
        for w in range(min(slice_hosts, n_nodes - len(nodes))):
            nodes.append(
                make_tpu_node(
                    f"gke-c{cluster}-s{i // clusters}-w{w}",
                    pool=pool,
                    cluster=cluster,
                    accelerator="tpu-v5-lite-podslice",
                    topology="4x8",
                    chips=4,
                    worker_id=w,
                    ready=rng.random() > 0.02,
                    age_seconds=rng.randrange(3600, 3600 * 24 * 30),
                )
            )
        i += 1

    phases = ["Running"] * 8 + ["Pending", "Failed"]
    for j in range(len(nodes)):
        # Exactly 3 pods per 4 nodes: the pod count lands in the SAME
        # power-of-two bucket as the node count (n/2 pods would pad to
        # the half-size bucket and fall off the square AOT table).
        if j % 4 == 3:
            continue
        phase = rng.choice(phases)
        pods.append(
            make_tpu_pod(
                f"vp-workload-{j}",
                namespace=f"team-{j % 5}",
                node=nodes[j]["metadata"]["name"] if phase != "Pending" else None,
                chips=4,
                phase=phase,
                age_seconds=rng.randrange(60, 3600 * 24 * 7),
                waiting_reason="Unschedulable" if phase == "Pending" else None,
            )
        )

    return {
        "nodes": nodes,
        "pods": pods,
        "daemonsets": [make_plugin_daemonset(desired=len(nodes))],
    }
