"""Deterministic fleet fixtures for the BASELINE configs."""

from .fixtures import (  # noqa: F401
    FIXTURE_NOW_EPOCH,
    FIXTURE_NOW_ISO,
    fleet_large,
    fleet_mixed,
    fleet_viewport,
    fleet_v5e4,
    fleet_v5p32,
    make_intel_node,
    make_intel_pod,
    make_plain_node,
    make_plugin_daemonset,
    make_plugin_pod,
    make_tpu_node,
    make_tpu_pod,
)
