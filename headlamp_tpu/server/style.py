"""Stylesheet for the dashboard host — keyed off the ``hl-*`` classes
the UI kit emits. Kept as a Python constant so the server stays a
single zero-dependency package."""

STYLESHEET = """
:root { --ok:#2e7d32; --warn:#ed6c02; --err:#d32f2f; --ink:#1a1a24;
        --muted:#667; --line:#e0e0e8; --bg:#f7f7fa; }
* { box-sizing:border-box; }
body { margin:0; font:14px/1.5 system-ui,sans-serif; color:var(--ink);
       background:var(--bg); }
.hl-nav { display:flex; gap:4px; padding:10px 16px; background:#fff;
          border-bottom:1px solid var(--line); position:sticky; top:0; }
.hl-nav a { padding:6px 12px; border-radius:6px; color:var(--ink);
            text-decoration:none; }
.hl-nav a.active { background:var(--bg); font-weight:600; }
.hl-nav .hl-refresh { margin-left:auto; color:var(--muted); }
main { max-width:1100px; margin:0 auto; padding:16px; }
.hl-section { background:#fff; border:1px solid var(--line);
              border-radius:8px; padding:14px 16px; margin:14px 0; }
.hl-section-title { margin:0 0 10px; font-size:16px; }
.hl-table { border-collapse:collapse; width:100%; }
.hl-table th { text-align:left; color:var(--muted); font-weight:600;
               border-bottom:1px solid var(--line); padding:6px 8px; }
.hl-table td { border-bottom:1px solid var(--line); padding:6px 8px;
               vertical-align:top; }
.hl-namevalue { display:grid; grid-template-columns:220px 1fr; gap:4px 12px;
                margin:0; }
.hl-namevalue dt { color:var(--muted); }
.hl-namevalue dd { margin:0; }
.hl-status { padding:2px 8px; border-radius:10px; font-size:12px;
             color:#fff; }
.hl-status-ok { background:var(--ok); } .hl-status-warn { background:var(--warn); }
.hl-status-err { background:var(--err); } .hl-status-neutral { background:var(--muted); }
.hl-error { background:#fdecea; border:1px solid var(--err); color:var(--err);
            border-radius:8px; padding:10px 14px; margin:14px 0; }
.hl-notice { background:#fff8e1; border:1px solid var(--warn);
             border-radius:8px; padding:10px 14px; margin:14px 0; }
.hl-empty-content { background:#fff; border:1px dashed var(--line);
                    border-radius:8px; padding:22px; text-align:center;
                    color:var(--muted); margin:14px 0; }
.hl-utilbar { position:relative; background:var(--bg); border:1px solid
              var(--line); border-radius:6px; height:20px; min-width:160px; }
.hl-utilbar-fill { height:100%; border-radius:5px; background:var(--ok); }
.hl-utilbar-warn .hl-utilbar-fill { background:var(--warn); }
.hl-utilbar-err .hl-utilbar-fill { background:var(--err); }
.hl-utilbar-label { position:absolute; inset:0; display:flex; align-items:center;
                    justify-content:center; font-size:11px; }
.hl-pctbar-track { display:flex; height:14px; border-radius:6px;
                   overflow:hidden; background:var(--bg); }
.hl-pctbar-part { background:var(--ok); }
.hl-pctbar-part:nth-child(2n) { background:#1565c0; }
.hl-pctbar-part:nth-child(3n) { background:var(--warn); }
.hl-pctbar-legend { color:var(--muted); font-size:12px; display:flex; gap:12px;
                    margin-top:4px; }
.hl-hint { color:var(--muted); font-size:12px; }
.hl-table-controls { display:flex; align-items:center; gap:16px; flex-wrap:wrap;
                     margin:4px 0 8px; }
.hl-filter-form { display:flex; gap:6px; }
.hl-filter-form input { padding:3px 8px; border:1px solid #c5ced6;
                        border-radius:4px; font-size:13px; }
.hl-filter-form button { padding:3px 10px; border:1px solid #c5ced6;
                         border-radius:4px; background:#fff; cursor:pointer; }
.hl-loader { padding:30px; text-align:center; color:var(--muted); }
.hl-mesh-grid { margin:10px 0; }
.hl-mesh-cell { position:absolute; border-radius:4px; border:1px solid #fff; }
.hl-worker-0 { background:#1565c0; --worker-color:#1565c0; }
.hl-worker-1 { background:#2e7d32; --worker-color:#2e7d32; }
.hl-worker-2 { background:#ed6c02; --worker-color:#ed6c02; }
.hl-worker-3 { background:#6a1b9a; --worker-color:#6a1b9a; }
.hl-worker-4 { background:#00838f; --worker-color:#00838f; }
.hl-worker-5 { background:#c62828; --worker-color:#c62828; }
.hl-worker-6 { background:#4e342e; --worker-color:#4e342e; }
.hl-worker-7 { background:#37474f; --worker-color:#37474f; }
.hl-mesh-down { opacity:0.35; border-style:dashed; }
/* Live-utilization heat bands (topology x telemetry join): the tint
   replaces the worker background; worker identity moves to the border
   via the per-worker custom property set above. */
/* border-color/width only — border-STYLE stays with the base/.hl-mesh-down
   rules so a not-ready worker keeps its dashed marker when tinted. */
.hl-heat-0 { background:#e8f0fe !important; border-color:var(--worker-color,#999); border-width:2px; }
.hl-heat-1 { background:#aecbfa !important; border-color:var(--worker-color,#999); border-width:2px; }
.hl-heat-2 { background:#fde293 !important; border-color:var(--worker-color,#999); border-width:2px; }
.hl-heat-3 { background:#f6ae6b !important; border-color:var(--worker-color,#999); border-width:2px; }
.hl-heat-4 { background:#ee675c !important; border-color:var(--worker-color,#999); border-width:2px; }
.hl-mesh-missing { background:repeating-linear-gradient(45deg,#ccc,#ccc 4px,
                   #eee 4px,#eee 8px) !important; }
.hl-mesh-links { color:var(--muted); font-size:12px; }
.hl-attention { border-color:var(--warn); }
/* Trace waterfall (/debug/traces/html, ADR-013): one .hl-trace section
   per request, span rows as label | proportional bar | duration. Bars
   position with inline margin-left/width percentages of the trace's
   total duration — the page is static HTML, so layout math happens at
   render time, not in CSS. */
.hl-trace-header { display:flex; align-items:center; gap:10px;
                   margin-bottom:8px; }
.hl-trace-header .hl-hint { margin-left:auto; }
.hl-trace-path { font-family:ui-monospace,monospace; font-weight:600; }
.hl-span-row { display:flex; align-items:center; gap:8px; font-size:12px;
               padding:2px 0; border-bottom:1px dotted var(--line); }
.hl-span-label { flex:0 0 240px; font-family:ui-monospace,monospace;
                 white-space:nowrap; overflow:hidden;
                 text-overflow:ellipsis; }
.hl-span-track { flex:1; position:relative; height:12px;
                 background:var(--bg); border-radius:4px; }
.hl-span-bar { height:100%; border-radius:4px; background:#1565c0;
               opacity:0.85; }
.hl-span-ms { flex:0 0 72px; text-align:right; color:var(--muted);
              font-variant-numeric:tabular-nums; }
.hl-span-attrs { flex:0 1 auto; color:var(--muted);
                 font-family:ui-monospace,monospace; white-space:nowrap;
                 overflow:hidden; text-overflow:ellipsis; }
/* SLO status (/sloz/html, ADR-016): one .hl-slo section per objective
   — state chip, per-window burn readouts colored against the page/warn
   thresholds, error-budget meter, exemplar links into the waterfall. */
.hl-slo-header { display:flex; align-items:center; gap:10px;
                 margin-bottom:8px; }
.hl-slo-header .hl-hint { margin-left:auto; }
.hl-slo-burns { display:flex; gap:16px; margin:6px 0; flex-wrap:wrap; }
.hl-slo-burn { display:flex; align-items:baseline; gap:6px;
               font-size:12px; padding:2px 8px; border-radius:4px;
               background:var(--bg); border:1px solid var(--line); }
.hl-slo-burn-window { color:var(--muted);
                      font-family:ui-monospace,monospace; }
.hl-slo-burn-rate { font-weight:600;
                    font-variant-numeric:tabular-nums; }
.hl-slo-burn-warn { border-color:var(--warn); }
.hl-slo-burn-warn .hl-slo-burn-rate { color:var(--warn); }
.hl-slo-burn-err { border-color:var(--err); }
.hl-slo-burn-err .hl-slo-burn-rate { color:var(--err); }
.hl-budgetbar { margin:6px 0; }
.hl-slo-exemplars a { margin-right:8px;
                      font-family:ui-monospace,monospace; }
.hl-slo-forecast { font-style:italic; }
/* Trend strips (/tpu/trends, ADR-018): fixed-bucket bar strips per
   captured series — newest at the right edge, gaps rendered as faint
   cells so an outage reads as an outage. */
.hl-trend-windows { display:flex; align-items:baseline; gap:8px;
                    margin-bottom:10px; font-size:13px;
                    color:var(--muted); }
.hl-trend-window { padding:2px 8px; border:1px solid var(--line);
                   border-radius:4px; text-decoration:none; }
.hl-trend-window.active { background:#1565c0; color:#fff;
                          border-color:#1565c0; }
.hl-trend-series { margin:8px 0 14px; }
.hl-trend-series-head { display:flex; align-items:baseline; gap:10px;
                        margin-bottom:4px; }
.hl-trend-series-head .hl-hint { margin-left:auto; font-size:12px;
                                 font-variant-numeric:tabular-nums; }
.hl-trend-strip { display:flex; align-items:flex-end; gap:1px;
                  height:36px; background:var(--bg);
                  border:1px solid var(--line); border-radius:4px;
                  padding:2px; }
.hl-trend-cell { flex:1; background:#1565c0; opacity:0.85;
                 border-radius:1px; min-height:1px; }
.hl-trend-gap { height:100%; background:var(--line); opacity:0.25; }
"""
