"""Server — the standalone dashboard host.

The reference is hosted by the Headlamp web app; this framework ships
its own host: a zero-dependency stdlib HTTP server that hydrates the
AcceleratorDataContext, renders registered routes to HTML, and serves
the sidebar navigation. Point it at a kube-apiserver (``kubectl proxy``)
or run it in demo mode against the BASELINE fixture fleets.
"""

from .app import DashboardApp, make_demo_transport

__all__ = ["DashboardApp", "make_demo_transport"]
