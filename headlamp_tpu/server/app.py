"""Dashboard HTTP host.

Serves the registered plugin surface over stdlib ``http.server``:

- ``GET <route.path>``        — render the route's page
- ``GET /refresh?back=<url>`` — imperative-track refresh then redirect
  (the manual refresh button, `OverviewPage.tsx:143-158`)
- ``GET /healthz``            — liveness + snapshot freshness JSON
- ``GET /metricsz``           — Prometheus text self-exposition (ADR-013)
- ``GET /sloz``               — SLO burn-rate report JSON (the HTML
  status page lives at the registered ``/sloz/html`` route, ADR-016)
- ``GET /debug/traces``       — recent request traces as JSON (the HTML
  waterfall lives at the registered ``/debug/traces/html`` route)
- ``GET /debug/flightz``      — flight-recorder wide events (pinned
  errored/SLO-violating requests first)

Cluster state comes from one AcceleratorDataContext synced at most once
per ``min_sync_interval_s`` (request-coalesced polling — the reactive
track's list+watch analogue without a background thread); the metrics
page triggers its own Prometheus fetch per view, matching the
reference's independent MetricsPage fetch cycle
(`MetricsPage.tsx:199-231`).

Demo mode (``python -m headlamp_tpu.server --demo v5p32``) wires a
MockTransport over the fixture fleets plus synthetic Prometheus data so
the full UI runs with zero cluster.
"""

from __future__ import annotations

import functools
import html
import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any
from urllib.parse import parse_qs, urlparse

from ..context.accelerator_context import AcceleratorDataContext, ClusterSnapshot
from ..gateway.shed import degraded_active
from ..history import HistoryStore, set_active_store
from ..metrics.client import fetch_tpu_metrics
from ..obs import slo as slo_mod
from ..obs.flight import flight_recorder, wide_event
from ..obs.jaxcost import ledger as jax_ledger
from ..obs.ledger import GenerationLedger
from ..obs.timeline import IncidentTimeline
from ..obs.metrics import registry as metrics_registry
from ..obs.profiler import attribution, profiler
from ..obs.propagate import parse_traceparent
from ..obs.trace import annotate, current_trace_id, span, trace_request, trace_ring
from ..push import PAGES as PUSH_PAGES
from ..push import PushPipeline, encode_body, format_event, set_active_push
from ..push.hub import worker_identity
from ..runtime.refresh import Refresher
from ..runtime.transfer import TransferBatch
from ..pages.native import native_node_page, native_pod_page
from ..registration import Registry, register_plugin
from ..transport.api_proxy import MockTransport, Transport
from ..ui import FragmentCache, FragmentPaint, render_html, set_active_fragments
from .style import STYLESHEET

#: Dynamic native-detail paths: /node/<name> and /pod/<ns>/<name>.
#: Kubernetes object names are DNS-1123 (lowercase alphanumerics, '-',
#: '.'), so the patterns are strict — anything else 404s rather than
#: reaching a renderer with attacker-shaped input.
_NODE_DETAIL_RE = re.compile(r"^/node/([a-z0-9.-]{1,253})$")
_POD_DETAIL_RE = re.compile(r"^/pod/([a-z0-9.-]{1,253})/([a-z0-9.-]{1,253})$")


@functools.lru_cache(maxsize=64)
def _nav_html(entries: tuple[tuple[str, str], ...], active: str) -> str:
    """Sidebar nav markup, memoized per (registry entries, active
    route): the entry set is fixed after plugin registration and there
    are ~a dozen routes, so in steady state every paint reuses one of
    a handful of strings instead of re-joining the nav (the invariant-
    subtree hoist from ISSUE 16)."""
    return "".join(
        f'<a href="{url}"'
        + (' class="active"' if url == active else "")
        + f">{label}</a>"
        for url, label in entries
    )


def _analytics_health() -> dict[str, Any]:
    """Rollup-calibration state for /healthz (ADR-008 observability):
    which backend at-scale requests would take right now, and the
    measured timings behind the choice. Import-guarded — a jax-less
    host serves Python unconditionally and reports just that."""
    try:
        import time as _time

        from ..analytics.stats import XLA_ROLLUP_MIN_NODES, calibration

        now = _time.monotonic()
        cal = {
            "calibrated": calibration.xla_ms is not None,
            # TTL state: stale timings mean the NEXT at-scale request
            # re-probes (chosen_backend answers "calibrating") — without
            # this an operator debugging the re-probe's latency spike
            # would see a healthy calibrated snapshot.
            "stale": calibration.expired(now),
            "age_s": (
                round(now - calibration.calibrated_at, 1)
                if calibration.calibrated_at is not None
                else None
            ),
            "xla_ms": (
                round(calibration.xla_ms, 2)
                if calibration.xla_ms is not None
                else None
            ),
            "python_ms_per_node": (
                round(calibration.python_ms_per_node, 5)
                if calibration.python_ms_per_node is not None
                else None
            ),
            "floor_nodes": XLA_ROLLUP_MIN_NODES,
            # Memoized backend breakage: non-null means at-scale
            # requests serve Python WITHOUT re-attempting device work
            # (N consecutive failures pinned this reason);
            # /refresh?recalibrate=1 clears it and forces a fresh probe.
            "broken_reason": calibration.broken_reason,
        }
        return cal
    except Exception as exc:  # noqa: BLE001 — health must never 500 on analytics
        # Degraded, not silent (ISSUE r07 satellite): a broken analytics
        # import used to report the same shape as "probe not yet run",
        # hiding real breakage behind a healthy-looking block. The error
        # TYPE is enough for an operator to grep; the message could
        # carry cluster strings and /healthz is unauthenticated.
        return {"calibrated": False, "error": type(exc).__name__}


def _runtime_health(
    transport: Any = None,
    refreshers: tuple[Refresher, ...] = (),
    gateway: Any = None,
    history: Any = None,
    push: Any = None,
    replication: Any = None,
    fragments: Any = None,
    workers: Any = None,
    scenarios: Any = None,
) -> dict[str, Any]:
    """Transfer-funnel, device-cache, transport-pool, and refresher
    counters for /healthz: how many blocking device_gets the process
    has paid, how often warm requests hit the device-resident fleet
    (ADR-012), how many TCP handshakes the keep-alive pool saved
    (ADR-014), and how often the stale-while-revalidate caches kept a
    fit off the request path (ADR-015). The ``transport`` block appears
    only when the app's transport is pooled (KubeTransport) —
    MockTransport-backed demo/test apps report the other blocks
    unchanged."""
    try:
        from ..runtime.device_cache import fleet_cache, warm_carries
        from ..runtime.transfer import transfer_stats
        from ..transport.pool import pool_of

        out = {
            "transfer": transfer_stats.snapshot(),
            "fleet_cache": fleet_cache.snapshot(),
            # Process-scoped warm-start carries (ADR-020): entries is
            # how many chip sets this process has learned params for.
            "warm_carries": {
                **warm_carries.counters(),
                "entries": len(warm_carries),
            },
        }
        pool = pool_of(transport)
        if pool is not None:
            out["transport"] = pool.snapshot()
        if refreshers:
            out["refresh"] = {r.name: r.snapshot() for r in refreshers}
        if gateway is not None:
            # Admission-layer view (ADR-017): queue depths, in-flight
            # renders, shed/coalesce counters, and the burn states the
            # shed policy last acted on.
            out["gateway"] = gateway.snapshot()
        if history is not None:
            # History-tier view (ADR-018): points/evictions/memory and
            # how far back /tpu/trends can currently answer.
            out["history"] = history.snapshot()
        if push is not None:
            # Push-pipeline view (ADR-021): connected SSE clients,
            # frames sent, evictions, resume fallbacks — the live-wall
            # triage block.
            out["push"] = push.snapshot()
        if replication is not None:
            # Read-tier view (ADR-025): leader publish/backlog state or
            # replica cursor/lag/staleness, depending on role.
            out["replication"] = replication.snapshot()
        if fragments is not None:
            # Fragment-cache view (ADR-027): entries/bytes/hit-rate —
            # the first stop when page.component dominates --attribute.
            out["render"] = fragments.snapshot()
        if workers is not None:
            # Multi-process plane view (ADR-029): every worker slot's
            # counters off the shared status board, plus which worker
            # answered this probe — triage must not depend on which
            # process the kernel handed the socket to.
            out["workers"] = workers.snapshot()
        if scenarios is not None:
            # Incident-drill view (ADR-030): present ONLY while a drill
            # is active — a probe reader must know the faults it is
            # seeing are rehearsed; steady-state probes stay
            # byte-stable against pre-ADR-030 expectations.
            drill = scenarios.health_block()
            if drill is not None:
                out["scenarios"] = drill
        # Burn-rate states per declared SLO (ADR-016): the one-line
        # answer a probe reader wants before opening /sloz.
        out["slo"] = slo_mod.engine().health_block()
        # JAX cost ledger (ADR-019): compiles vs warm dispatches per
        # jitted program, plus counted host↔device bytes — the "is the
        # device path recompiling?" answer without opening a profile.
        out["jax"] = jax_ledger().snapshot()
        # AOT registry (ADR-020): did startup absorb the compiles, and
        # are requests hitting precompiled buckets? The phase split in
        # the ledger block above plus this state answers "why did the
        # first request spike" without a profile.
        from ..models.aot import registry as _aot_registry

        out["jax"]["aot"] = _aot_registry().snapshot()
        # Profiler vitals only (counters + overhead) — the call tree
        # itself lives at /debug/profilez, far too big for a probe.
        prof = profiler()
        overhead = prof.overhead_ns_per_sample()
        out["profiler"] = {
            **prof.counters(),
            "nodes": prof.node_count(),
            "overhead_ns_per_sample": (
                round(overhead, 1) if overhead is not None else None
            ),
        }
        return out
    except Exception as exc:  # noqa: BLE001 — health must never 500 on analytics
        # An empty block read as "no runtime telemetry wired"; a named
        # error reads as what it is — degraded observability.
        return {"error": type(exc).__name__}


def _runtime_counters(
    transport: Any = None,
    refreshers: tuple[Refresher, ...] = (),
    gateway: Any = None,
    history: Any = None,
    push: Any = None,
) -> dict[str, float]:
    """Flat dotted monotone-counter snapshot for the flight recorder's
    before/after delta. Deliberately NOT _runtime_health: this runs
    twice per recorded request, so it reads each component's dedicated
    ``counters()`` view — plain int loads, no locks, no SLO window
    evaluation, and none of the gauge-like floats (RTT EWMAs, budget
    ratios) that would turn the 'what this request moved' delta into
    noise."""
    try:
        from ..runtime.device_cache import fleet_cache, warm_carries
        from ..runtime.transfer import transfer_stats
        from ..transport.pool import pool_of
    except Exception:  # noqa: BLE001 — recording must never fail a request
        return {}
    out: dict[str, float] = {}
    for prefix, counters in (
        ("transfer", transfer_stats.counters()),
        ("fleet_cache", fleet_cache.counters()),
        ("warm_carries", warm_carries.counters()),
    ):
        for key, value in counters.items():
            out[f"{prefix}.{key}"] = value
    pool = pool_of(transport)
    if pool is not None:
        for key, value in pool.counters().items():
            out[f"transport.{key}"] = value
    for refresher in refreshers:
        for key, value in refresher.counters().items():
            out[f"refresh.{refresher.name}.{key}"] = value
    if gateway is not None:
        for key, value in gateway.counters().items():
            out[f"gateway.{key}"] = value
    if history is not None:
        for key, value in history.counters().items():
            out[f"history.{key}"] = value
    if push is not None:
        for key, value in push.counters().items():
            out[f"push.{key}"] = value
    # ADR-019: process-wide singletons (ledger + profiler), same
    # bleed-between-neighbours caveat as every other counter here.
    for key, value in jax_ledger().counters().items():
        out[f"jax.{key}"] = value
    # ADR-020: AOT registry bucket traffic and donation savings.
    try:
        from ..models.aot import registry as _aot_registry

        for key, value in _aot_registry().counters().items():
            out[f"jax.aot.{key}"] = value
    except Exception:  # noqa: BLE001 — recording must never fail a request
        pass
    for key, value in profiler().counters().items():
        out[f"profiler.{key}"] = value
    return out


def _force_recalibration() -> None:
    """Operator recovery lever: ``/refresh?recalibrate=1`` drops the
    rollup timings AND any pinned broken-backend state, so the next
    at-scale request re-probes. Explicit opt-in only — the bare
    /refresh is the routine header link on every page, and wiring
    either reset to it would defeat both the probe amortization (per-
    click recalibration re-pays ~600 ms) and the broken-backend
    memoization (every navigation refresh would re-pay the failed
    compile three more times). Import-guarded like _analytics_health."""
    try:
        from ..analytics.stats import calibration

        calibration.reset()
    except Exception:  # noqa: BLE001 — refresh must never 500 on analytics
        pass
    try:
        # The re-probe should measure what steady state serves — warm
        # device-resident arrays — but a recalibration is also the
        # operator's "something is off on the device" lever, so drop
        # the resident fleets and let the next sync/request re-upload.
        from ..runtime.device_cache import fleet_cache

        fleet_cache.invalidate()
    except Exception:  # noqa: BLE001
        pass


class DashboardApp:
    def __init__(
        self,
        transport: Transport,
        *,
        registry: Registry | None = None,
        min_sync_interval_s: float = 5.0,
        clock: Any = time.time,
        monotonic: Any = time.monotonic,
        pod_field_selector: str | None = None,
        fragments: Any = None,
    ) -> None:
        self._ctx = AcceleratorDataContext(
            transport, pod_field_selector=pod_field_selector, clock=clock
        )
        self._transport = transport
        self._registry = registry if registry is not None else register_plugin()
        self._min_sync = min_sync_interval_s
        # Clock-skew discipline (ADR-013): ``clock`` (wall) is ONLY for
        # displayed timestamps (snapshot fetched_at, page "now");
        # ``monotonic`` drives every elapsed/TTL/age computation, so an
        # NTP step or operator date change can never wedge sync
        # coalescing or serve an immortal cache entry.
        self._clock = clock
        self._mono = monotonic
        # -inf, not 0.0: time.monotonic's epoch is arbitrary (boot time
        # on Linux) and can be small on a fresh host — 0.0 would silently
        # suppress the first inline sync for up to min_sync seconds.
        self._last_sync = float("-inf")
        #: Monotonic stamp of the last completed sync, for the /healthz
        #: staleness/wedge math (the snapshot's own fetched_at stays
        #: wall-clock, for display).
        self._last_snapshot_mono: float | None = None
        # ThreadingHTTPServer serves requests concurrently; the context
        # and the check-then-act on _last_sync are not thread-safe, so
        # all state mutation funnels through one lock (renders of an
        # already-built snapshot stay lock-free).
        self._lock = threading.Lock()
        # Stale-while-revalidate caches (ADR-015): the refresher owns
        # TTL/grace/single-flight; the app owns the keys (Prometheus
        # target + chip set for forecasts — see _metrics_key) and the
        # epoch. The pre-r09 design held a plain lock across the whole
        # fetch/fit, so a TTL lapse stalled every concurrent metrics
        # view behind a multi-second cold fit.
        self._metrics_refresher = Refresher(
            "metrics",
            ttl_s=self.METRICS_TTL_S,
            grace_s=self.METRICS_GRACE_S,
            monotonic=monotonic,
        )
        self._forecast_refresher = Refresher(
            "forecast",
            ttl_s=self.FORECAST_TTL_S,
            grace_s=self.FORECAST_GRACE_S,
            monotonic=monotonic,
        )
        #: History tier (ADR-018): per-app so tests and replay runs
        #: never share series; the module-level active-store weakref
        #: only feeds the /metricsz gauges (latest app wins).
        self.history = HistoryStore(monotonic=monotonic)
        set_active_store(self.history)
        # The process SLO engine mirrors its paint-latency series into
        # (and trains its budget forecast from) this app's store —
        # weakref inside, latest app wins, same as the gauges above.
        slo_mod.engine().history_store = self.history
        # Capture seam: every successful scrape the metrics refresher
        # stores — background refits AND cold foreground fills — lands
        # in the history store. The hook runs after _store releases the
        # refresher lock, and in steady state on the refit worker, so
        # capture never extends the request critical path.
        self._metrics_refresher.on_store = self._capture_metrics_store
        #: Warm-start carries per forecast key (ADR-015): fitted params
        #: + optimizer state handed back to the next (re)fit for the
        #: same fleet. Process-scoped since ADR-020 (the
        #: ``runtime.device_cache.warm_carries`` tier): carries survive
        #: app reconstruction, so a rebuilt app — fresh serve, CLI
        #: one-shot, the bench's fresh-app discipline — warm-starts
        #: from what the process already learned for that chip set.
        from ..runtime.device_cache import warm_carries

        self._warm_forecast_states = warm_carries
        #: Bumped by /refresh. Cache entries record the epoch current
        #: when their fetch *started*; a mismatched epoch invalidates
        #: them. This lets refresh invalidate without touching the
        #: refreshers' locks — computes run for seconds, and the
        #: refresh redirect must never stall behind those.
        self._cache_epoch = 0
        #: Last fully-built snapshot, published atomically (single
        #: reference assignment) after each sync — /healthz reads this
        #: without locking, so liveness probes can never stall behind a
        #: slow cluster sync holding self._lock.
        self._last_snapshot: Any = None
        #: Stop event of the background sync thread, when one is running
        #: (see start_background_sync) — its liveness suppresses inline
        #: syncs on the request path.
        self._background_stop: threading.Event | None = None
        #: Wakes the background loop early — set by /refresh so a manual
        #: refresh shortens the reactive track's staleness to one sync,
        #: not one full interval.
        self._background_wake = threading.Event()
        self._background_interval: float | None = None
        #: Consecutive syncs that raised or produced an errors-bearing
        #: snapshot. Written by whichever path syncs (background loop or
        #: inline); read racily by /healthz — int updates are atomic
        #: enough for a health probe.
        self._sync_failures = 0
        #: Serializes background-loop lifecycle transitions (restart vs
        #: a stop handle's set()): the stale-handle guard is a
        #: check-then-act and must not interleave with the restart's
        #: enable_watch(). Reentrant because a restart set()s the old
        #: handle while already holding it.
        self._bg_lock = threading.RLock()
        #: Per-request transfer accounting (written in handle()'s
        #: finally, read racily by bench/healthz — GIL-atomic int ops).
        #: ``last_request_device_gets`` is the number ISSUE r06's
        #: acceptance pins at 1 for a warm-cache request.
        self.requests_served = 0
        self.request_device_gets = 0
        self.last_request_device_gets = 0
        # Process-level request instruments (ADR-013). get-or-create:
        # tests build many DashboardApps per process and they must share
        # the registry rather than collide on re-registration.
        self._req_hist = metrics_registry.histogram(
            "headlamp_tpu_request_duration_seconds",
            "End-to-end handle() latency per route template "
            "(non-5xx responses; errors count in requests_total).",
            labels=("route",),
        )
        self._req_total = metrics_registry.counter(
            "headlamp_tpu_requests_total",
            "Requests served, by route template and status code.",
            labels=("route", "status"),
        )
        self._sync_fail_total = metrics_registry.counter(
            "headlamp_tpu_sync_failures_total",
            "Cluster syncs that raised or produced an errors-bearing snapshot.",
        )
        #: The admission layer (ADR-017), created lazily by serve() (or
        #: injected by tests/bench). None for direct handle() callers —
        #: the CLI and unit tests measure the handler, not admission.
        self.gateway: Any = None
        #: Push pipeline (ADR-021): snapshot differ + SSE broadcast
        #: hub. Constructed eagerly — it spawns no threads (the /events
        #: handler threads belong to the socket server, and the differ
        #: runs on whichever thread syncs). The module-level weakref
        #: only feeds the connected-clients gauge; latest app wins.
        #: Fragment cache (ADR-027): rendered HTML per differ key,
        #: keyed by the same (generation, epoch, degraded) invariants
        #: as the ADR-021 ETag. Per-app (bench/tests build many apps
        #: per process; two fleets must never share bytes); pass
        #: ``fragments=False`` to disable — the non-incremental oracle
        #: the byte-identity tests compare against.
        if fragments is False:
            self.fragments = None
        else:
            self.fragments = fragments if fragments is not None else FragmentCache()
            set_active_fragments(self.fragments)
        #: Generation provenance ledger (ADR-028): every lifecycle
        #: stage of every snapshot generation this process touches —
        #: scrape, sync, publish, apply, diff, first paint — stamped on
        #: the injected clocks. ReplicaApp re-roles it to "replica".
        self.ledger = GenerationLedger(
            monotonic=monotonic, wall=clock, role="leader"
        )
        #: Incident timeline (ADR-030): scenario injections, SLO state
        #: flips, gateway shed/restore events, hub evictions, and the
        #: ledger's leadership transitions merged into one ordered view
        #: at /debug/incidentz. Always present; cheap when idle.
        self.incidents = IncidentTimeline(monotonic=monotonic, wall=clock)
        self.incidents.ledger = self.ledger
        self.push = PushPipeline(
            monotonic=monotonic, fragments=self.fragments, ledger=self.ledger
        )
        self.push.hub.eviction_observers.append(self.incidents.eviction_observer)
        set_active_push(self.push)
        #: Read-tier hook (ADR-025). On a leader: a BusPublisher —
        #: _record_sync hands it every published generation, and
        #: /replicate/bus serves its backlog. On a replica: the
        #: BusConsumer (set by its constructor). None (default) keeps
        #: single-process serving byte-identical to pre-replication.
        self.replication: Any = None
        #: Multi-process plane hook (ADR-029). On a worker process: a
        #: _BoardHealth adapter over the shared status board, so
        #: /healthz reports runtime.workers — the whole board, stamped
        #: with which worker answered. None everywhere else.
        self.workers: Any = None

    @property
    def registry(self) -> Registry:
        return self._registry

    def snapshot_generation(self) -> int:
        """The ADR-012 generation stamp of the last published snapshot
        (0 before any sync) — one ingredient of the gateway's coalesce
        key: requests spanning a snapshot change must not share bytes.
        Reads the atomically-published reference, never locks."""
        snap = self._last_snapshot
        if snap is None:
            return 0
        for state in snap.providers.values():
            version = getattr(state.view, "version", None)
            if version:
                return int(version)
        return 0

    def start_background_sync(self, interval_s: float | None = None) -> threading.Event:
        """Periodic cluster sync off the request path — the closest
        server-side analogue of the reference's live list+watch
        (`IntelGpuDataContext.tsx:98-99`): page views read the freshest
        completed sync instead of paying for one inline. Returns a stop
        Event (the thread is a daemon either way). Sync failures are
        absorbed — the next tick retries, and the request path's own
        coalesced sync still works."""
        app = self

        class _StopEvent(threading.Event):
            """Setting stop also wakes the loop so it exits promptly
            instead of sleeping out the rest of the interval, and —
            only while this is still the ACTIVE loop's stop handle —
            turns watch mode back off, because the re-enabled inline
            request-path sync must cost fast LISTs, not two full
            server-side watch windows per page view. A stale handle's
            set() must not degrade a newer live loop: the check-then-act
            serializes with restarts under ``_bg_lock``."""

            def set(self) -> None:  # noqa: A003 (threading.Event API)
                super().set()
                with app._bg_lock:
                    if app._background_stop is self:
                        app._ctx.enable_watch(False)
                self.wake.set()

        with self._bg_lock:
            # Restarting replaces any live loop: stop it first so two
            # loops never share the context, and give the new loop its
            # OWN wake event — an orphaned old loop must not consume a
            # /refresh wake meant for the current one.
            if self._background_live():
                self._background_stop.set()
            wake = threading.Event()
            self._background_wake = wake
            stop = _StopEvent()
            stop.wake = wake
            interval = (
                interval_s if interval_s is not None else max(self._min_sync, 1.0)
            )
            self._background_interval = interval
            self._background_stop = stop
            # Steady-state background syncing transfers watch deltas,
            # not the whole fleet — enabled only after this handle is
            # the active one, so a concurrent stale set() cannot undo
            # it (it re-checks under the same lock and no-ops).
            self._ctx.enable_watch()

        def sync_once() -> None:
            # Each background tick runs under its own trace (ADR-028):
            # the pool stamps the tick's trace id onto outbound scrapes
            # and the publisher records it as the generation's
            # provenance. Deliberately NOT ring-recorded — a quiet
            # cluster's ticks would evict every real page trace.
            with trace_request("/sync", wall=self._clock):
                try:
                    with self._lock:
                        self.ledger.scrape_started()
                        self._ctx.sync()
                        self._last_sync = self._mono()
                        snap = self._ctx.snapshot()
                        self._last_snapshot = snap
                        self._last_snapshot_mono = self._mono()
                except Exception:  # noqa: BLE001 — keep the heartbeat alive
                    self._record_sync(None)
                else:
                    self._record_sync(snap)
                    self._warm_device_cache(snap)

        def loop() -> None:
            sync_once()  # hydrate immediately; first page view must not block
            while True:
                wake.wait(interval)
                wake.clear()
                if stop.is_set():
                    return
                sync_once()

        # While the thread runs, page views never sync inline — that is
        # the flag's whole promise. The stop event re-enables inline
        # syncing (checked per request, so a stopped thread does not
        # strand the app with a permanently stale snapshot).
        threading.Thread(target=loop, daemon=True, name="hl-tpu-sync").start()
        return stop

    def _warm_device_cache(self, snap: Any) -> None:
        """Background-sync hook: upload the TPU fleet's columnar arrays
        to device as soon as a new snapshot lands, so the first request
        against it is already a cache hit (the upload happens off the
        request path — the entire point of the device-resident cache).
        Gated on the XLA floor: below it the measured policy serves the
        Python rollup, which never touches the arrays. Any failure is
        absorbed — a broken device backend degrades requests to the
        Python fallback via the calibration machinery, and the warm
        must not kill the sync heartbeat rehearsing the same error."""
        try:
            state = snap.providers.get("tpu")
            if state is None or state.view.version is None:
                return
            from ..analytics.stats import XLA_ROLLUP_MIN_NODES

            if len(state.view.nodes) < XLA_ROLLUP_MIN_NODES:
                return
            from ..runtime.device_cache import fleet_cache

            fleet_cache.warm(state.view)
            # ADR-020: whatever node/pod buckets this fleet actually
            # encodes to get their rollup executable compiled in the
            # background — observed shapes, not guesses, drive the
            # backfill, and it rides the same off-request-path hook as
            # the device upload.
            from ..analytics.encode import _bucket
            from ..models.aot import registry as _aot_registry

            _aot_registry().ensure_rollup_shapes(
                _bucket(max(len(state.view.nodes), 1)),
                _bucket(max(len(state.view.pods), 1)),
            )
        except Exception:  # noqa: BLE001 — warm is an optimization only
            pass

    def _capture_metrics_store(self, key: Any, value: Any) -> None:
        """Refresher on_store hook: record each successfully fetched
        metrics snapshot into the history tier. A cached failure (None —
        Prometheus down) appends nothing: gaps in history ARE the record
        of the outage."""
        if value is not None and getattr(value, "chips", None):
            self.history.record_scrape(value)

    def _record_sync(self, snap: Any) -> None:
        """Track consecutive failing syncs for /healthz, and capture the
        generation/node-count/error-count of every completed sync into
        the history tier (ADR-018) — both capture points (this and the
        metrics refresher hook) run on sync/refit threads, off the
        request path. A sync counts as failed when it raised (snap is
        None) or when its snapshot carries reactive-track errors —
        transport failures never raise out of ``ctx.sync()`` (they
        degrade into ``snapshot.errors``), so the error streams ARE the
        failure signal."""
        if snap is not None:
            generation = 0
            for state in snap.providers.values():
                version = getattr(state.view, "version", None)
                if version:
                    generation = int(version)
                    break
            self.history.record_sync(
                generation=generation,
                nodes=len(snap.all_nodes or []),
                errors=len(snap.errors),
            )
            # Ledger stamp (ADR-028): the scrape became this generation
            # — BEFORE the differ and publisher hooks, so their stamps
            # (diff_framed, published) measure against it.
            self.ledger.synced(generation, trace_id=current_trace_id())
            # Differ hook (ADR-021): a generation bump diffs the new
            # snapshot's page models against the previous generation's
            # and broadcasts patch frames to the connected SSE clients.
            # The metrics/forecast arguments are non-blocking PEEKS —
            # the sync heartbeat must not grow a Prometheus probe chain
            # or a jax fit. on_snapshot absorbs its own exceptions and
            # no-ops on a clean tick (generation unchanged).
            self.push.on_snapshot(
                snap,
                generation=generation,
                metrics=self._peek_metrics,
                forecast=self._peek_forecast,
            )
            # Replication publish hook (ADR-025): a leader's bus gets
            # the same (snapshot, peeks) the differ just got — same
            # non-blocking peek stance, same absorb-everything contract
            # (BusPublisher.on_snapshot never raises).
            replication = self.replication
            if replication is not None and hasattr(replication, "on_snapshot"):
                replication.on_snapshot(
                    snap,
                    generation=generation,
                    metrics=self._peek_metrics,
                    forecast=self._peek_forecast,
                )
        if snap is not None and not snap.errors:
            self._sync_failures = 0
        else:
            self._sync_failures += 1
            self._sync_fail_total.inc()

    def _background_live(self) -> bool:
        return self._background_stop is not None and not self._background_stop.is_set()

    def _synced_snapshot(self) -> ClusterSnapshot:
        # With background sync live, page views read the atomically
        # published snapshot WITHOUT taking the sync lock: the loop
        # holds self._lock across each tick, and with watch enabled a
        # tick spans the bounded watch windows (seconds against a real
        # apiserver) — a page view must never stall behind that.
        with span("sync.snapshot") as node:
            if degraded_active() and self._last_snapshot is not None:
                # Gateway-degraded render (ADR-017): serve the last
                # published snapshot without syncing — under a paging
                # burn rate a stale paint beats queueing a cluster sync
                # behind the overload. Falls through to the normal path
                # only when no snapshot exists yet (first-ever request
                # mid-incident still needs SOME data).
                if node is not None:
                    node.attrs["source"] = "degraded-stale"
                return self._last_snapshot
            if self._background_live():
                snap = self._last_snapshot
                if snap is not None:
                    if node is not None:
                        node.attrs["source"] = "background"
                    return snap
                # Not yet hydrated: fall through and build one under the
                # lock (races the loop's first tick harmlessly — ctx.sync
                # and snapshot builds are serialized by the lock).
            with self._lock:
                now = self._mono()
                if (
                    not self._background_live()
                    and now - self._last_sync >= self._min_sync
                ):
                    self.ledger.scrape_started()
                    self._ctx.sync()
                    self._last_sync = now
                    snap = self._ctx.snapshot()
                    self._record_sync(snap)
                    self._last_snapshot_mono = self._mono()
                    annotate(source="inline-sync")
                else:
                    snap = self._ctx.snapshot()
                    annotate(source="coalesced")
                self._last_snapshot = snap
                annotate(nodes=len(snap.all_nodes or []))
                return snap

    #: Consecutive failing syncs at which /healthz flips ``ok`` to false
    #: — one blip must not restart a pod, a persistent failure must not
    #: hide behind a hard-coded ``"ok": true``.
    HEALTH_FAILURE_THRESHOLD = 3
    #: With background sync live, a snapshot older than this many
    #: intervals means the loop is wedged (thread died, sync hanging) —
    #: also flips ``ok`` even when no individual sync reported failure.
    HEALTH_MAX_STALE_INTERVALS = 3.0
    #: Staleness floor for the wedged check: a tick legitimately spans
    #: the two bounded watch windows plus imperative-track fetches, so
    #: at small intervals ``intervals × interval`` alone would flap
    #: ok:false on a healthy cluster mid-tick. Wedged detection can
    #: afford to be slow; liveness flapping cannot.
    HEALTH_MIN_STALE_S = 30.0

    #: Forecast results are cached this long — the history grid only
    #: gains a point per step anyway, and the fit (jax compile + scan)
    #: must not run on every page view.
    FORECAST_TTL_S = 60.0
    #: Stale-while-revalidate grace (ADR-015): past the TTL but within
    #: this TOTAL age, a forecast is served immediately while a
    #: background worker refits — no request ever pays the fit. Ten
    #: minutes: a forecast that old is still directionally honest for a
    #: capacity dashboard, and only a key idle longer than this pays a
    #: blocking fit again.
    FORECAST_GRACE_S = 600.0
    #: Instant metrics fetches are briefly cached too: the Prometheus
    #: round-trip is cheap but not free, and without a TTL every page
    #: view pays it while the forecast beside it is served from cache.
    METRICS_TTL_S = 5.0
    #: Grace for the metrics scrape — matches METRICS_PEEK_MAX_AGE_S:
    #: the same "a minute-old snapshot beats blocking" judgement the
    #: heatmap peek already made.
    METRICS_GRACE_S = 60.0

    @staticmethod
    def _metrics_key(metrics: Any) -> Any:
        """Content key for the forecast cache: the Prometheus target plus
        the chip set. Chip *identity* (not sample values) is the right
        granularity — values change every scrape, but a forecast is only
        wrong-for-the-fleet when the chips themselves change."""
        return (
            metrics.namespace,
            metrics.service,
            frozenset((c.node, c.accelerator_id) for c in metrics.chips),
        )

    def _cached_metrics(self) -> Any:
        """`fetch_tpu_metrics` behind the stale-while-revalidate
        refresher: fresh within METRICS_TTL_S, served-stale (with a
        background refetch) within METRICS_GRACE_S, blocking only when
        cold. A failed fetch (None) is also cached — a down Prometheus
        must not re-pay the full probe chain on every view within the
        TTL. The epoch is read BEFORE the fetch: a /refresh arriving
        mid-fetch bumps it and the entry is born stale, so the next
        view refetches; the freshness window starts AFTER the fetch
        (refresher stamps at store time), so a slow probe chain never
        burns its own TTL."""
        # TTLs re-read per call: the class attrs are operator/test knobs
        # and must keep working when overridden after construction.
        r = self._metrics_refresher
        r.ttl_s = self.METRICS_TTL_S
        r.grace_s = max(self.METRICS_GRACE_S, self.METRICS_TTL_S)
        if degraded_active():
            # Gateway-degraded (ADR-017): stale-only. peek never
            # computes, so the Prometheus probe chain stays off the
            # overloaded path; a cold cache renders the no-data state.
            return r.peek("metrics", epoch=self._cache_epoch)
        return r.get(
            "metrics",
            lambda: fetch_tpu_metrics(self._transport, clock=self._clock),
            epoch=self._cache_epoch,
        )

    #: How stale a cached telemetry snapshot may be and still tint the
    #: topology heatmap. Deliberately looser than METRICS_TTL_S: the
    #: metrics PAGE re-fetches at 5 s for freshness, but a tint from a
    #: minute-old snapshot beats no tint — and the serving TTL can
    #: legitimately lapse inside one slow metrics request (probe chain +
    #: first forecast compile), which must not blank the heatmap.
    METRICS_PEEK_MAX_AGE_S = 60.0

    def _peek_metrics(self) -> Any:
        """The cached metrics snapshot IF recent (see
        METRICS_PEEK_MAX_AGE_S), else None — never fetches. For pages
        where telemetry is a progressive enhancement (the topology
        heatmap): they must not pay the Prometheus probe chain, only
        reuse what a recent metrics view already paid for. Age is judged
        from the refresher's monotonic fetch stamp, not the serving TTL
        (and not the snapshot's wall-clock fetched_at, which an NTP step
        could swing either way — ADR-013 clock audit). Non-blocking by
        construction: Refresher.peek only touches the entry map, never a
        compute."""
        return self._metrics_refresher.peek(
            "metrics",
            epoch=self._cache_epoch,
            max_age_s=self.METRICS_PEEK_MAX_AGE_S,
        )

    def _peek_forecast(self) -> Any:
        """Cached forecast for the metrics peek's fleet, or None —
        never fetches, never fits (Refresher.peek only touches the
        entry map). For the push differ: the /tpu/metrics page model
        should diff whatever forecast a recent metrics view already
        paid for, and a cold cache simply diffs the page without its
        forecast rows."""
        metrics = self._peek_metrics()
        if metrics is None or not metrics.chips:
            return None
        return self._forecast_refresher.peek(
            self._metrics_key(metrics), epoch=self._cache_epoch
        )

    #: Warm-start carries kept per forecast key, LRU-capped inside the
    #: process-wide ``warm_carries`` tier (ADR-020). Small on purpose:
    #: each carry holds ~115k float32 params + adam moments (<2 MB); a
    #: dashboard serves a handful of fleets, not hundreds.
    WARM_STATE_MAX_KEYS = 8

    def _forecast_for(self, metrics: Any) -> Any:
        """Forecast view for the metrics page, or None. None whenever
        the analytics extras (jax/optax) are absent — the forecast is a
        progressive enhancement, never a hard dependency of the page —
        or history is too thin to be honest. Stale-while-revalidate
        cached, keyed on the metrics content (see `_metrics_key`): a
        TTL lapse within the grace window serves the previous view
        immediately and refits on a background worker, so the
        multi-second fit never lands on a user request (the pre-r09
        design held a lock across the fit and stalled every concurrent
        metrics view — ISSUE r09's satellite regression test pins the
        fix)."""
        if metrics is None or not metrics.chips:
            return None
        key = self._metrics_key(metrics)
        r = self._forecast_refresher
        r.ttl_s = self.FORECAST_TTL_S
        r.grace_s = max(self.FORECAST_GRACE_S, self.FORECAST_TTL_S)
        if degraded_active():
            # Gateway-degraded (ADR-017): a cached forecast still
            # renders, but a cold key returns None — the page draws
            # without the forecast panel rather than paying a jax fit
            # while the burn rate pages.
            return r.peek(key, epoch=self._cache_epoch)
        return r.get(
            key,
            lambda: self._compute_forecast(metrics),
            epoch=self._cache_epoch,
        )

    def _metrics_and_forecast(self) -> tuple[Any, Any]:
        """Metrics + forecast for the metrics route. Sequential on
        purpose since the refreshers landed (ADR-015): in steady state
        BOTH calls are cache reads — stale values serve immediately
        while background workers revalidate — so there is nothing left
        to overlap; the r07-era fetch∥forecast thread-pool overlap was
        retired with the blocking paths it hid."""
        with span("page.data.metrics"):
            metrics = self._cached_metrics()
        with span("page.data.forecast"):
            forecast = self._forecast_for(metrics)
        return metrics, forecast

    def _compute_forecast(self, metrics: Any) -> Any:
        # Delegates to the shared host glue (models.service) so the CLI
        # and HTTP consumers render identical metrics pages; the HTTP
        # host uses the incremental entry so fitted params + optimizer
        # state carry across TTL windows (ADR-015 warm starts). Import
        # is lazy and guarded: models.service itself imports
        # jax-dependent modules at call time, but the import alone must
        # not break a host without the analytics extras.
        try:
            from ..models.service import compute_forecast_incremental
        except ImportError:
            return None
        key = self._metrics_key(metrics)
        # take(), not get(): the warm program donates the carry's
        # buffers, so the store must hand it to exactly one fit. The
        # new carry is stored back below.
        state = self._warm_forecast_states.take(key)
        # ADR-020: hand the fused rollup+forecast path the current TPU
        # fleet view — when the warm carry and a precompiled bucket line
        # up, rollup + refinement run as ONE donated device program and
        # the overview's next fleet_stats serves the parked rollup.
        fleet_view = None
        try:
            snap = self._last_snapshot
            provider_state = (
                snap.providers.get("tpu") if snap is not None else None
            )
            if provider_state is not None:
                fleet_view = provider_state.view
        except Exception:  # noqa: BLE001 — fused path is an optimization
            fleet_view = None
        view, new_state = compute_forecast_incremental(
            self._transport,
            metrics,
            state=state,
            clock=self._clock,
            # ADR-018: once the captured tier holds a full training
            # window, fits train on real history (and say so in the
            # view's data_source) instead of the live range query.
            history_store=self.history,
            fleet_view=fleet_view,
        )
        if new_state is not None:
            self._warm_forecast_states.store(key, new_state)
        if view is not None and view.warm_demotion_reason is not None:
            self._forecast_refresher.note_demotion()
        return view

    # ------------------------------------------------------------------
    # Request handling (framework-level, server-agnostic)
    # ------------------------------------------------------------------

    #: Route labels whose traces stay OUT of the ring: a kubelet probing
    #: /healthz every 5 s would evict every real page trace within
    #: minutes, Prometheus scraping /metricsz likewise, and tracing the
    #: trace endpoints would make the ring describe itself. Their
    #: request METRICS still record — only ring retention is skipped.
    _RING_EXCLUDED = frozenset(
        {
            "/healthz",
            "/metricsz",
            "/debug/traces",
            "/debug/traces/html",
            "/sloz",
            "/sloz/html",
            "/debug/flightz",
            "/debug/profilez",
            "/debug/profilez/folded",
            "/debug/profilez/html",
            "/debug/generationz",
            "/debug/generationz/html",
            "/debug/incidentz",
            "/debug/incidentz/html",
        }
    )

    def _route_label(self, path: str) -> str:
        """Bounded-cardinality route template for metric labels. Dynamic
        detail paths collapse to their template and unknown paths to
        'other' — a URL scanner walking random paths must not mint one
        label child (and one ring entry name) per probe."""
        route_path = urlparse(path).path.rstrip("/") or "/tpu"
        if route_path in (
            "/healthz",
            "/refresh",
            "/metricsz",
            "/debug/traces",
            "/sloz",
            "/debug/flightz",
            "/debug/profilez",
            "/debug/profilez/folded",
            "/debug/generationz",
            "/debug/incidentz",
            "/events",
        ):
            return route_path
        if _NODE_DETAIL_RE.match(route_path):
            return "/node/{name}"
        if _POD_DETAIL_RE.match(route_path):
            return "/pod/{namespace}/{name}"
        if self._registry.route_for(route_path) is not None:
            return route_path
        return "other"

    def handle(
        self,
        path: str,
        *,
        accept: str | None = None,
        gateway_info: dict[str, Any] | None = None,
        traceparent: str | None = None,
    ) -> tuple[int, str, str]:
        """(status, content_type, body) for a GET. Pure enough to test
        without sockets. Never raises: route errors become a 500 page
        (a traceback must not leak into a response, and one broken
        route must not kill the handler thread). ``accept`` is the
        request's Accept header — only /metricsz consults it (OpenMetrics
        content negotiation); every other route ignores it.

        Every request runs inside its own TransferBatch scope: stages
        that produce device arrays (XLA rollup, forecast, mesh shards)
        register into it via the runtime transfer funnel, and the first
        consumer flushes ALL of them in one blocking ``jax.device_get``
        — one tunnel RTT per request instead of one per stage. The
        batch also counts the request's blocking fetches, which is the
        ``device_gets_per_request`` number bench.py reports.

        Telemetry (ADR-013): each request also runs inside a
        ``trace_request`` scope — stage spans opened anywhere below
        (sync, analytics, transfer flush, render) attach to it via the
        contextvar, and the completed trace lands in the ring — and
        records its latency/status into the Prometheus registry. Both
        happen HERE, not in ``serve()``, so the CLI-less test path and
        any future host are measured identically."""
        t0 = time.perf_counter()
        route_label = self._route_label(path)
        batch = TransferBatch()
        status = 500
        recorded = route_label not in self._RING_EXCLUDED
        counters_before: dict[str, float] | None = None
        if recorded:
            # Flight-recorder baseline: monotone runtime counters
            # snapshotted around the request so the wide event carries
            # what THIS request moved (process-wide reads — a concurrent
            # neighbour's activity can bleed in; accepted for a triage
            # surface, ADR-016). The cheap counters() view, NOT
            # _runtime_health: evaluating every SLO window twice per
            # request would dwarf the 5.3 µs slo_eval budget.
            counters_before = _runtime_counters(
                self._transport,
                (self._metrics_refresher, self._forecast_refresher),
                gateway=self.gateway,
                history=self.history,
                push=self.push,
            )
        # Inbound traceparent (ADR-028): a caller that already runs a
        # trace — a replica polling the bus, a fan-out peer, a fronting
        # gateway — names it here, and this request's trace records it
        # as its remote parent. This process still mints its OWN id.
        remote = parse_traceparent(traceparent)
        # attribution() publishes this thread's route + trace id for the
        # sampling profiler (ADR-019). Entered AFTER trace_request so
        # current_trace_id() resolves to this request's trace.
        with trace_request(
            path,
            enabled=recorded,
            wall=self._clock,
            remote_parent=remote.trace_id if remote is not None else None,
        ) as trace, attribution(route_label):
            try:
                if gateway_info:
                    # Marker span carrying the admission-side story
                    # (priority class, queue wait, degraded flag). Zero
                    # duration by design: the wait already happened on
                    # the request thread before this worker ran; only
                    # its ATTRS matter to the waterfall. Opened here —
                    # not in the gateway — because trace_request's
                    # contextvar scope starts on this (worker) thread.
                    with span("gateway.admission", **gateway_info):
                        pass
                with batch.scope():
                    status, content_type, body = self._handle(path, accept)
                    return status, content_type, body
            except Exception as e:  # noqa: BLE001 — error boundary
                body = self._page_html(
                    "Error",
                    "<div class='hl-error' role='alert'>Internal error: "
                    f"{html.escape(type(e).__name__)}: {html.escape(str(e))}</div>",
                )
                return 500, "text/html", body
            finally:
                self.requests_served += 1
                self.request_device_gets += batch.blocking_gets
                self.last_request_device_gets = batch.blocking_gets
                duration_s = time.perf_counter() - t0
                # Observed INSIDE the trace scope so the histogram
                # bucket's exemplar carries this request's trace id.
                # 5xx responses stay OUT of the latency histogram: the
                # SLO engine counts them once as bad events through the
                # requests_total 5xx feed, and a fast 500 must not also
                # register as a good latency observation (it would halve
                # bad_fraction during an error storm and delay paging).
                if status < 500:
                    self._req_hist.observe(duration_s, route=route_label)
                self._req_total.inc(route=route_label, status=str(status))
                trace_dict = None
                if trace is not None:
                    trace.finish(
                        route=route_label,
                        status=status,
                        device_gets=batch.blocking_gets,
                    )
                    trace_dict = trace.to_dict()
                    trace_ring.record(trace_dict)
                if recorded:
                    counters_after = _runtime_counters(
                        self._transport,
                        (self._metrics_refresher, self._forecast_refresher),
                        gateway=self.gateway,
                        history=self.history,
                        push=self.push,
                    )
                    violations = slo_mod.engine().violations(
                        route_label, duration_s, status
                    )
                    # Replication context for the wide event (ADR-028
                    # satellite): role + applied generation + bus
                    # cursor, when a bus endpoint is wired. Subset of
                    # the healthz block — the triage keys, not the
                    # whole counter set.
                    replication_info = None
                    replication = self.replication
                    if replication is not None:
                        try:
                            block = replication.snapshot()
                            replication_info = {
                                k: block[k]
                                for k in (
                                    "role",
                                    "cursor",
                                    "last_generation",
                                    "applied",
                                )
                                if k in block
                            }
                        except Exception:  # noqa: BLE001 — triage only
                            replication_info = None
                    flight_recorder.record(
                        wide_event(
                            path=path,
                            route=route_label,
                            status=status,
                            duration_s=duration_s,
                            trace=trace_dict,
                            violations=violations,
                            counters_before=counters_before,
                            counters_after=counters_after,
                            gateway=gateway_info,
                            replication=replication_info,
                        ),
                        pinned=bool(violations) or status >= 500,
                    )

    def _handle(self, path: str, accept: str | None = None) -> tuple[int, str, str]:
        parsed = urlparse(path)
        route_path = parsed.path.rstrip("/") or "/tpu"

        if route_path == "/healthz":
            # Liveness must never block: reads the atomically-published
            # last snapshot instead of taking self._lock (held across
            # full cluster syncs — seconds at fleet scale, exactly when
            # a kubelet probe timing out would restart a healthy pod).
            # It also must not build a snapshot itself: a concurrent
            # sync may be mid-mutation (nodes updated, workloads not
            # yet), and a half-synced snapshot must not get cached.
            snap = self._last_snapshot
            failures = self._sync_failures
            failing = failures >= self.HEALTH_FAILURE_THRESHOLD
            background = self._background_live()
            if snap is None:
                body = json.dumps(
                    {
                        "ok": not failing,
                        "loading": True,
                        "errors": [],
                        "consecutive_sync_failures": failures,
                        "background_sync": background,
                        # Snapshot-independent; monitors read it during
                        # startup too, when "probe not yet run" is the
                        # most informative state.
                        "analytics": _analytics_health(),
                        "runtime": _runtime_health(
                            self._transport,
                            (self._metrics_refresher, self._forecast_refresher),
                            gateway=self.gateway,
                            history=self.history,
                            push=self.push,
                            replication=self.replication,
                            fragments=self.fragments,
                            workers=self.workers,
                            scenarios=self.incidents,
                        ),
                    }
                )
                return 200, "application/json", body
            # Age on the monotonic stamp, not fetched_at (wall): a
            # backwards NTP step would otherwise fake freshness and hide
            # a wedged loop; a forwards one would flap ok:false. The
            # stamp is None only before any completed sync, and then
            # snap is None too (checked above), so 0.0 is unreachable
            # paranoia, not a real state.
            stamp = self._last_snapshot_mono
            age = max(self._mono() - stamp, 0.0) if stamp is not None else 0.0
            interval = self._background_interval
            wedged = (
                background
                and interval is not None
                and age
                > max(
                    self.HEALTH_MAX_STALE_INTERVALS * interval,
                    self.HEALTH_MIN_STALE_S,
                )
            )
            body = json.dumps(
                {
                    "ok": not failing and not wedged,
                    "loading": snap.loading,
                    "errors": snap.errors,
                    "fetched_at": snap.fetched_at,
                    "last_sync_age_s": round(age, 3),
                    "consecutive_sync_failures": failures,
                    "background_sync": background,
                    "analytics": _analytics_health(),
                    "runtime": _runtime_health(
                        self._transport,
                        (self._metrics_refresher, self._forecast_refresher),
                        gateway=self.gateway,
                        history=self.history,
                        push=self.push,
                        replication=self.replication,
                        fragments=self.fragments,
                        workers=self.workers,
                        scenarios=self.incidents,
                    ),
                }
            )
            return 200, "application/json", body

        if route_path == "/metricsz":
            # Prometheus self-exposition (ADR-013). Like /healthz this
            # must never block or 500: render() walks lock-light
            # in-memory instruments and callback gauges swallow their
            # own errors, so a scrape is safe at any process state.
            # Exemplars only ride the OpenMetrics rendering — a classic
            # text-format scraper would fail the whole scrape on them
            # (ADR-016) — so the format is negotiated from Accept.
            from ..obs.metrics import (
                OPENMETRICS_CONTENT_TYPE,
                TEXT_CONTENT_TYPE,
                negotiate_openmetrics,
            )

            if negotiate_openmetrics(accept):
                body = metrics_registry.render(openmetrics=True)
                return 200, OPENMETRICS_CONTENT_TYPE, body
            return 200, TEXT_CONTENT_TYPE, metrics_registry.render()

        if route_path == "/debug/traces":
            # JSON twin of /debug/traces/html — the ring's raw contents
            # for jq/curl; entries are frozen dicts, so dumps never
            # races an in-flight request.
            body = json.dumps(
                {"capacity": trace_ring.capacity, "traces": trace_ring.snapshot()}
            )
            return 200, "application/json", body

        if route_path == "/sloz":
            # Burn-rate report (ADR-016): states, per-window burn, budget
            # remaining, latency exemplars, and the self-forecast's
            # projected budget exhaustion. JSON twin of /sloz/html.
            return 200, "application/json", json.dumps(slo_mod.engine().report())

        if route_path == "/debug/flightz":
            # Wide-event dump: pinned (errored / SLO-violating) requests
            # first, then recent healthy traffic. Frozen dicts, same
            # no-race guarantee as /debug/traces.
            snapshot = flight_recorder.snapshot()
            body = json.dumps(
                {
                    "capacity": flight_recorder.capacity,
                    "pinned_capacity": flight_recorder.pinned_capacity,
                    "pinned": snapshot["pinned"],
                    "recent": snapshot["recent"],
                }
            )
            return 200, "application/json", body

        if route_path == "/debug/generationz":
            # Generation provenance ledger (ADR-028): recent
            # generations' lifecycle stamps and stage lags, freshness
            # breaches pinned past rotation, leadership transitions
            # interleaved. JSON twin of /debug/generationz/html.
            return 200, "application/json", json.dumps(self.ledger.snapshot())

        if route_path == "/debug/incidentz":
            # Incident timeline (ADR-030): scenario injections, SLO
            # flips, shed/restore events, hub evictions, and leadership
            # transitions in one ordered list. JSON twin of
            # /debug/incidentz/html.
            return 200, "application/json", json.dumps(self.incidents.snapshot())

        if route_path == "/debug/profilez":
            # Sampling-profiler state (ADR-019): counters, per-route
            # stack attribution, and the bounded call tree. ?burst=N
            # raises the sampling rate for N seconds (clamped) so an
            # operator chasing a live drift gets resolution on demand.
            prof = profiler()
            query = parse_qs(parsed.query)
            granted: float | None = None
            if "burst" in query:
                try:
                    granted = prof.burst(float(query["burst"][0]))
                except ValueError:
                    granted = None
            out = prof.snapshot()
            if granted is not None:
                out["burst_granted_s"] = granted
            return 200, "application/json", json.dumps(out)

        if route_path == "/debug/profilez/folded":
            # Flamegraph folded-stack text — pipe straight into any
            # flamegraph renderer.
            return 200, "text/plain", profiler().folded()

        if route_path == "/refresh":
            # With background sync live, waking the loop covers BOTH
            # tracks (its sync() runs reactive + imperative) and the
            # redirect never waits on the sync lock — which the loop
            # holds across whole ticks, watch windows included. Without
            # it, run the imperative refresh inline as the reference's
            # refreshKey effect does (`IntelGpuDataContext.tsx:109-111`).
            if self._background_live():
                self._background_wake.set()
            else:
                with self._lock:
                    self._ctx.refresh()
            # Manual refresh also invalidates the metrics + forecast
            # caches — the user is explicitly asking for fresh data, and
            # serving a cached Prometheus view from before the click
            # would make the button look broken. Done by bumping the
            # epoch, NOT by taking the cache locks: those are held
            # across multi-second fetches/fits, and the redirect must
            # return immediately.
            self._cache_epoch += 1
            query = parse_qs(parsed.query)
            if query.get("recalibrate", ["0"])[0] in ("1", "true"):
                _force_recalibration()
            back = query.get("back", ["/tpu"])[0]
            # Only registered route paths and strictly-shaped native
            # detail paths may be redirect targets: kills open redirects
            # ('//evil', absolute URLs) and header injection (CR/LF) in
            # one allowlist check.
            if self._registry.route_for(back) is None and not (
                _NODE_DETAIL_RE.match(back) or _POD_DETAIL_RE.match(back)
            ):
                back = "/tpu"
            return 302, back, ""

        # Native host surface: the views the detail sections and column
        # processors inject into (`index.tsx:152-182`).
        node_match = _NODE_DETAIL_RE.match(route_path)
        if node_match:
            snap = self._synced_snapshot()
            with span("page.component", kind="native-node-detail"):
                el = native_node_page(
                    snap,
                    node_match.group(1),
                    now=self._clock(),
                    registry=self._registry,
                )
            status = 404 if el.props.get("data-notfound") else 200
            with span("render.html"):
                body = self._page_html(
                    f"Node {node_match.group(1)}", render_html(el), route_path
                )
            return status, "text/html", body
        pod_match = _POD_DETAIL_RE.match(route_path)
        if pod_match:
            snap = self._synced_snapshot()
            with span("page.component", kind="native-pod-detail"):
                el = native_pod_page(
                    snap,
                    pod_match.group(1),
                    pod_match.group(2),
                    now=self._clock(),
                    registry=self._registry,
                )
            status = 404 if el.props.get("data-notfound") else 200
            with span("render.html"):
                body = self._page_html(
                    f"Pod {pod_match.group(2)}", render_html(el), route_path
                )
            return status, "text/html", body

        route = self._registry.route_for(route_path)
        if route is None:
            return 404, "text/html", self._page_html("Not Found", "<p>No such page.</p>")

        snap = self._synced_snapshot()
        now = self._clock()
        paging: dict[str, Any] = {}
        if route.paged:
            params = parse_qs(parsed.query)
            try:
                paging["page"] = int(params.get("page", ["1"])[0])
            except ValueError:
                paging["page"] = 1
            # The query is render-escaped downstream like any other
            # cluster string; cap its length so a hostile URL cannot
            # make the substring filter arbitrarily expensive.
            paging["query"] = params.get("q", [""])[0][:253]
        if route.windowed:
            # Cursor-window params (ADR-026). Forwarded only when
            # present so their absence keeps the legacy rendering
            # byte-identical; the viewport layer clamps the limit and
            # treats any malformed cursor as "start over".
            params = parse_qs(parsed.query)
            if "limit" in params:
                try:
                    paging["limit"] = int(params["limit"][0])
                except ValueError:
                    pass
            if "cursor" in params:
                paging["cursor"] = params["cursor"][0][:512]
        # Data acquisition runs under its own span (ADR-027): the old
        # layout billed the Prometheus fetch + forecast fit to
        # page.component, so --attribute pointed at the renderer when
        # the cost was the data path. page.component now means
        # component build + changed-fragment re-render, nothing else.
        page_data: Any = None
        if route.kind == "metrics":
            with span("page.data", kind=route.kind):
                page_data = self._metrics_and_forecast()
        elif route.kind == "intel-metrics":
            from ..metrics.intel_client import fetch_intel_gpu_metrics

            with span("page.data", kind=route.kind):
                page_data = fetch_intel_gpu_metrics(
                    self._transport, clock=self._clock
                )
        paint = self._fragment_paint(route_path)
        with span("page.component", kind=route.kind):
            if route.kind == "metrics":
                metrics, forecast = page_data
                el = route.component(metrics, forecast)
            elif route.kind == "intel-metrics":
                el = route.component(page_data)
            elif route.kind == "topology":
                # Cache PEEK only: the heatmap is a progressive
                # enhancement; the topology paint must never pay the
                # Prometheus chain.
                el = route.component(snap, metrics=self._peek_metrics())
            elif route.kind == "native-nodes":
                el = route.component(snap, now=now, registry=self._registry, **paging)
            elif route.kind == "traces":
                # The waterfall page renders the ring itself — no
                # snapshot/now, by design: it must work even when the
                # cluster sync is the thing being debugged.
                el = route.component(trace_ring.snapshot())
            elif route.kind == "slo":
                # Same debugging-the-debugger discipline as the trace
                # page: renders the engine's report, never the cluster
                # snapshot, so it paints even mid-incident.
                el = route.component(slo_mod.engine().report())
            elif route.kind == "profile":
                # Flame view over the profiler snapshot — no cluster
                # snapshot either, for the same reason.
                el = route.component(profiler().snapshot())
            elif route.kind == "generations":
                # Provenance timeline over the ledger snapshot (ADR-
                # 028) — no cluster snapshot, so it paints even when
                # the feed being debugged is the thing that is stale.
                el = route.component(self.ledger.snapshot())
            elif route.kind == "incidents":
                # Incident timeline (ADR-030) — renders the merged
                # event log alone, no cluster snapshot: mid-incident is
                # exactly when this page must still paint.
                el = route.component(self.incidents.snapshot())
            elif route.kind == "trends":
                # Pure function of the store's windowed view (ADR-018):
                # no snapshot, no sync — trends must paint even when
                # the cluster sync is the thing being investigated.
                # ?window= selects the lookback; the store clamps it to
                # [1 s, retention], so a hostile query can only change
                # how much retained data renders, never how much exists.
                params = parse_qs(parsed.query)
                try:
                    window_s = float(params.get("window", ["3600"])[0])
                except ValueError:
                    window_s = 3600.0
                # ?metric= switches the view to the ADR-026 browse mode
                # (every series of one metric, label-sorted and
                # cursor-windowed) — the escape hatch from the grouped
                # view's busiest-N cap.
                metric = params.get("metric", [""])[0][:253]
                series_limit: int | None = None
                if "limit" in params:
                    try:
                        series_limit = int(params["limit"][0])
                    except ValueError:
                        series_limit = None
                series_cursor = params.get("cursor", [None])[0]
                if series_cursor:
                    series_cursor = series_cursor[:512]
                el = route.component(
                    self.history.trend_view(
                        window_s=window_s,
                        metric=metric,
                        series_cursor=series_cursor,
                        series_limit=series_limit,
                    )
                )
            elif route.kind == "viewport":
                # Drill-down surface (ADR-026): ?region= names the
                # rollup level (also the SSE region key); the cursor
                # window only applies at slice depth.
                params = parse_qs(parsed.query)
                region = params.get("region", [""])[0][:253]
                vp_limit: int | None = None
                if "limit" in params:
                    try:
                        vp_limit = int(params["limit"][0])
                    except ValueError:
                        vp_limit = None
                vp_cursor = params.get("cursor", [None])[0]
                if vp_cursor:
                    vp_cursor = vp_cursor[:512]
                el = route.component(
                    snap, now=now, region=region, limit=vp_limit, cursor=vp_cursor
                )
            else:
                el = route.component(snap, now=now, **paging)
            if paint is not None:
                # Changed-fragment re-render (ADR-027): resolve every
                # stale boundary into the cache HERE, so the build span
                # keeps covering all tree construction work…
                paint.prerender(el)
        if paint is not None:
            # …while cached-byte assembly bills to its own stage. A
            # warm paint spends ~nothing here; a paint that shows
            # fragment.splice dominating has a salt churning per
            # request (see OPERATIONS.md triage).
            with span(
                "fragment.splice",
                rendered=paint.rendered,
                spliced=paint.spliced,
            ):
                inner = paint.splice(el)
        else:
            inner = None
        with span("render.html"):
            if inner is None:
                inner = render_html(el)
            body = self._page_html(route.name, inner, route_path)
        # First-paint stamp (ADR-028): AFTER the bytes are built —
        # observational only, so paints/ETags/push frames stay byte-
        # identical — and only the FIRST paint of a generation counts
        # (the ledger dedupes; later paints are a no-op dict probe).
        self.ledger.paint(
            self.snapshot_generation(), trace_id=current_trace_id()
        )
        return 200, "text/html", body

    def _fragment_paint(self, page: str) -> Any:
        """The paint-scoped fragment context for ``page`` (None when
        fragments are disabled): the cache plus this paint's ADR-021
        ETag invariants — generation, /refresh epoch, degraded flag."""
        cache = self.fragments
        if cache is None:
            return None
        return FragmentPaint(
            cache,
            page=page,
            generation=self.snapshot_generation(),
            epoch=self._cache_epoch,
            degraded=degraded_active(),
        )

    def _page_html(self, title: str, body: str, active: str = "") -> str:
        nav = _nav_html(
            tuple(
                (e.url, e.label)
                for e in self._registry.sidebar_entries
                if e.parent is not None
            ),
            active,
        )
        refresh = f'<a class="hl-refresh" href="/refresh?back={active or "/tpu"}">Refresh</a>'
        return (
            "<!doctype html><html><head><meta charset='utf-8'>"
            f"<title>{title} · TPU Dashboard</title>"
            f"<style>{STYLESHEET}</style></head>"
            f"<body><nav class='hl-nav'>{nav}{refresh}</nav>"
            f"<main>{body}</main></body></html>"
        )

    # ------------------------------------------------------------------
    # Socket server
    # ------------------------------------------------------------------

    def ensure_gateway(self, **overrides: Any) -> Any:
        """The app's RenderGateway (ADR-017), created on first use.
        Socket serving ALWAYS routes through it — serve() calls this —
        so admission policy (bounded pool, burn-rate shed, coalescing)
        can never be skipped by a wiring mistake; direct ``handle()``
        calls remain the unit-test/CLI seam. ``overrides`` forward to
        the RenderGateway constructor (bench/test knobs: workers, queue
        depths, timeouts)."""
        if self.gateway is None:
            from ..gateway import RenderGateway, set_active

            self.gateway = RenderGateway(
                self.handle,
                route_label=self._route_label,
                generation=self.snapshot_generation,
                epoch=lambda: self._cache_epoch,
                monotonic=self._mono,
                **overrides,
            )
            set_active(self.gateway)
            # ADR-021: the gateway adopts the push pipeline — its
            # snapshot gains the SSE connection registry, and the hub
            # sheds DEBUG-class streams off the same paging policy.
            self.gateway.attach_push(self.push)
            # ADR-030: shed/degrade/paging/restore rulings land on the
            # incident timeline through the observer seam.
            self.gateway.shed_policy.observers.append(
                self.incidents.gateway_observer
            )
        return self.gateway

    def open_event_stream(self, path: str, *, last_event_id: str | None = None) -> Any:
        """Admit one ``/events`` SSE subscription (ADR-021) — the
        accounting half of the endpoint, separated from the socket loop
        so tests drive the whole protocol without sockets. Parses
        ``?pages=`` (comma-separated, unknown pages dropped, empty →
        all diffable pages) and ``?class=debug`` (opts the stream into
        the first-shed class — an always-on debug wall volunteers to be
        the first capacity recovered under paging burn).

        SLO feed, exactly once: the stream counts into requests_total
        at admission (status 200) and NEVER into the render-latency
        histogram — a connection's lifetime is not a paint latency, and
        frames ride the broadcast path, not renders."""
        query = parse_qs(urlparse(path).query)
        region = query.get("region", [""])[0][:253]
        if region:
            # Region-scoped stream (ADR-026): ?region=cluster/3/slice/7
            # subscribes to that drill-down region's frames only —
            # steady-state bytes scale with the region, not the fleet.
            # The path is canonicalized through the viewport parser; an
            # unparseable region falls back to the full page set (the
            # stream still works, it just is not narrowed).
            from ..viewport import parse_region, region_path

            parsed_region = parse_region(region)
            if parsed_region is not None:
                pages = ["region:" + region_path(*parsed_region)]
            else:
                pages = list(PUSH_PAGES)
        else:
            requested = [
                p for p in query.get("pages", [""])[0].split(",") if p
            ]
            pages = [p for p in requested if p in PUSH_PAGES] or list(PUSH_PAGES)
        priority = (
            "debug" if query.get("class", [""])[0] == "debug" else "interactive"
        )
        self._req_total.inc(route="/events", status="200")
        return self.push.hub.subscribe(
            pages, last_event_id=last_event_id, priority=priority
        )

    def serve(
        self,
        host: str = "127.0.0.1",
        port: int = 8631,
        *,
        reuse_port: bool = False,
        listen_socket: Any = None,
    ) -> ThreadingHTTPServer:
        """Build the HTTP server (caller runs ``serve_forever``).

        ADR-029 multi-process knobs: ``reuse_port`` lets N worker
        processes bind the same address (SO_REUSEPORT — the kernel
        load-balances accepts); ``listen_socket`` adopts a pre-bound
        listener inherited across a fork (the fd-passing strategy on
        hosts without SO_REUSEPORT). Default: plain single-process
        bind, byte-identical to the pre-worker behavior."""
        app = self
        gateway = self.ensure_gateway()
        # Always-on low-rate sampler (ADR-019). Here, not in __init__:
        # constructing an app must never spawn threads (tests build
        # hundreds of apps); only a socket-serving host profiles itself.
        profiler().start()
        # AOT startup compiles (ADR-020): a daemon thread lowers and
        # compiles every hot program at its canonical buckets while the
        # socket starts listening — requests that arrive before it
        # finishes just miss (plain jit path, counted); once it is done
        # the request path never pays a compile. Same never-in-__init__
        # rule as the profiler, and guarded: a jax-less host parks the
        # registry "unavailable" inside the thread, never breaks serve.
        try:
            from ..models.aot import registry as _aot_registry

            _aot_registry().compile_startup()
        except Exception:  # noqa: BLE001 — AOT is an optimization only
            pass

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                if urlparse(self.path).path.rstrip("/") == "/events":
                    # SSE stream (ADR-021): parked on a plain handler
                    # thread in the hub's condition wait — NEVER on a
                    # render-pool worker; a wall of idle dashboards
                    # must not occupy render capacity.
                    self._serve_events()
                    return
                if urlparse(self.path).path.rstrip("/") == "/replicate/bus":
                    # Snapshot bus pull (ADR-025): replicas resume by
                    # Last-Generation cursor. Bypasses the gateway —
                    # payload_after is a backlog copy (microseconds),
                    # and replica pulls must not queue behind renders.
                    self._serve_bus()
                    return
                response = gateway.handle(
                    self.path,
                    accept=self.headers.get("Accept"),
                    if_none_match=self.headers.get("If-None-Match"),
                    traceparent=self.headers.get("traceparent"),
                )
                status, content_type, body = response[:3]
                if status == 302:
                    self.send_response(302)
                    self.send_header("Location", content_type)
                    self.end_headers()
                    return
                if status == 304:
                    # RFC 7232: no body, no Content-Type — just the
                    # validators/freshness headers the gateway stamped.
                    self.send_response(304)
                    for name, value in response.headers:
                        self.send_header(name, value)
                    self.end_headers()
                    return
                data = body.encode()
                encoding = None
                if status == 200:
                    # The strong ETag the gateway stamped keys the gzip
                    # output cache: same validator, same bytes, so a
                    # repeat 200 reuses the compression (ADR-021).
                    etag = next(
                        (v for n, v in response.headers if n.lower() == "etag"),
                        None,
                    )
                    data, encoding = encode_body(
                        data, self.headers.get("Accept-Encoding"), etag=etag
                    )
                self.send_response(status)
                self.send_header("Content-Type", f"{content_type}; charset=utf-8")
                if status == 200:
                    # The representation varies by negotiation even
                    # when this response shipped identity.
                    self.send_header("Vary", "Accept-Encoding")
                if encoding is not None:
                    self.send_header("Content-Encoding", encoding)
                self.send_header("Content-Length", str(len(data)))
                for name, value in response.headers:
                    self.send_header(name, value)
                self.end_headers()
                self.wfile.write(data)

            def _serve_bus(self) -> None:
                replication = app.replication
                if replication is None or not hasattr(replication, "payload_after"):
                    self.send_response(404)
                    self.end_headers()
                    return
                from ..push.hub import parse_last_event_id

                cursor = parse_last_event_id(self.headers.get("Last-Generation"))
                # Leader-side stitch (ADR-028): the polling replica's
                # traceparent names ITS poll trace — this serve joins
                # it as a child across the process boundary. Ring-
                # recorded only when records actually shipped; a 1 Hz
                # stream of empty polls must not rotate real traces
                # out of the 64-slot ring.
                remote = parse_traceparent(self.headers.get("traceparent"))
                with trace_request(
                    "/replicate/bus",
                    wall=app._clock,
                    remote_parent=(
                        remote.trace_id if remote is not None else None
                    ),
                ) as trace:
                    with span("replicate.serve", cursor=cursor or 0):
                        payload = replication.payload_after(cursor).encode()
                    if trace is not None and payload.count(b"\n") > 1:
                        trace.finish(
                            route="/replicate/bus", status=200, device_gets=0
                        )
                        trace_ring.record(trace.to_dict())
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header(
                    "X-Headlamp-Generation", str(replication.last_generation)
                )
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def _serve_events(self) -> None:
                sub = app.open_event_stream(
                    self.path, last_event_id=self.headers.get("Last-Event-ID")
                )
                hub = app.push.hub
                try:
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    self.send_header("Cache-Control", "no-cache")
                    self.send_header(
                        "X-Headlamp-Generation", str(app.snapshot_generation())
                    )
                    # Multi-process serving (ADR-029): which worker this
                    # stream is pinned to. Connection pinning is what
                    # keeps SSE per-worker; the header makes the pin
                    # observable (and testable) from the client side.
                    worker = worker_identity()
                    if worker is not None:
                        self.send_header("X-Headlamp-Worker", worker)
                    self.end_headers()
                    while True:
                        event = hub.next_event(sub)
                        if event is None:
                            return
                        self.wfile.write(format_event(event).encode())
                        self.wfile.flush()
                        if event.get("kind") == "bye":
                            return
                except OSError:
                    # Client went away mid-stream — the normal way an
                    # SSE connection ends; eviction accounting already
                    # happened if the hub initiated it.
                    pass
                finally:
                    hub.unsubscribe(sub)

            def log_message(self, *args: Any) -> None:
                pass

        if listen_socket is not None:
            # Adopt the supervisor's pre-bound, pre-listening socket:
            # skip bind/activate entirely and serve its accept queue.
            server = ThreadingHTTPServer((host, port), Handler, bind_and_activate=False)
            server.socket.close()
            server.socket = listen_socket
            server.server_address = listen_socket.getsockname()[:2]
        elif reuse_port:
            import socket as _socket

            class _ReusePortServer(ThreadingHTTPServer):
                def server_bind(self) -> None:
                    if hasattr(_socket, "SO_REUSEPORT"):
                        self.socket.setsockopt(
                            _socket.SOL_SOCKET, _socket.SO_REUSEPORT, 1
                        )
                    super().server_bind()

            server = _ReusePortServer((host, port), Handler)
        else:
            server = ThreadingHTTPServer((host, port), Handler)
        return server


# ---------------------------------------------------------------------------
# Demo mode
# ---------------------------------------------------------------------------

def make_demo_transport(fleet_name: str = "v5p32") -> MockTransport:
    """MockTransport serving a fixture fleet (via
    ``fixtures.fleet_transport``) plus synthetic Prometheus data — the
    zero-cluster path for demos, verification, and benches."""
    from ..fleet import fixtures as fx

    fleets = {
        "v5e4": fx.fleet_v5e4,
        "v5p32": fx.fleet_v5p32,
        "mixed": fx.fleet_mixed,
        "large": lambda: fx.fleet_large(1024),
    }
    fleet = fleets[fleet_name]()
    t = fx.fleet_transport(fleet)
    add_demo_prometheus(t, fleet)
    return t


def add_demo_prometheus(t: MockTransport, fleet: dict) -> MockTransport:
    """Wire synthetic Prometheus (instant + range queries) for a fixture
    fleet onto an existing transport — shared by demo mode and bench.py
    so the benched scrape→paint path exercises the same series the demo
    serves."""
    # Synthetic Prometheus: deterministic per-chip utilization.
    import urllib.parse

    def q(promql: str) -> str:
        return (
            "/api/v1/namespaces/monitoring/services/prometheus-k8s:9090"
            f"/proxy/api/v1/query?query={urllib.parse.quote(promql, safe='')}"
        )

    tpu_nodes = [
        n["metadata"]["name"]
        for n in fleet["nodes"]
        if "cloud.google.com/gke-tpu-accelerator" in n["metadata"].get("labels", {})
    ]

    def vec(values: list[tuple[dict, float]]) -> dict:
        return {
            "status": "success",
            "data": {
                "resultType": "vector",
                "result": [
                    {"metric": labels, "value": [0, str(v)]} for labels, v in values
                ],
            },
        }

    GIB = 1024**3
    util, used, total = [], [], []
    for i, node in enumerate(tpu_nodes[:64]):
        for chip in range(4):
            labels = {"node": node, "accelerator_id": str(chip)}
            util.append((labels, round(0.35 + 0.13 * ((i * 4 + chip) % 5), 2)))
            used.append((labels, (8 + (i + chip) % 7) * GIB))
            total.append((labels, 16 * GIB))
    t.add(q("1"), {"status": "success", "data": {"resultType": "scalar", "result": [0, "1"]}})

    # Intel i915 hwmon series for any Intel nodes in the fleet (the
    # reference's own metric surface, metrics.ts:101-116).
    from ..domain.intel import is_intel_gpu_node
    from ..metrics.intel_client import INTEL_QUERIES

    intel_nodes = [
        n["metadata"]["name"] for n in fleet["nodes"] if is_intel_gpu_node(n)
    ]
    uname: list[tuple[dict, float]] = []
    if intel_nodes:
        chips_s, power_s, tdp_s = [], [], []
        for i, node in enumerate(intel_nodes):
            instance = f"10.1.0.{i + 1}:9100"
            uname.append(({"instance": instance, "nodename": node}, 1))
            labels = {"instance": instance, "chip": "card0", "chip_name": "i915"}
            chips_s.append((labels, 1))
            power_s.append((labels, 18.5 + 3 * i))
            tdp_s.append((labels, 120.0))
        t.add(q(INTEL_QUERIES["node_map"]), vec(uname))
        t.add(q(INTEL_QUERIES["chips"]), vec(chips_s))
        t.add(q(INTEL_QUERIES["power"]), vec(power_s))
        t.add(q(INTEL_QUERIES["tdp"]), vec(tdp_s))
    t.add(q("tensorcore_utilization"), vec(util))
    t.add(q("hbm_bytes_used"), vec(used))
    t.add(q("hbm_bytes_total"), vec(total))

    # Batched scrape (ADR-015): the client's default fan-out issues
    # matcher-joined `{__name__=~...}` queries; serve them the union of
    # the same samples with __name__ injected for the demux, so the
    # batched and per-metric paths return identical values. Batches
    # whose members have no demo data are left unregistered — the
    # client's fallback re-asks per metric, exercising the real policy.
    from ..metrics.client import (
        LOGICAL_METRICS,
        NODE_MAP_QUERY,
        batched_instant_queries,
    )

    demo_series: dict[str, list[tuple[dict, float]]] = {
        "tensorcore_utilization": util,
        "hbm_bytes_used": used,
        "hbm_bytes_total": total,
        NODE_MAP_QUERY: uname,
    }
    batchable = [NODE_MAP_QUERY]
    for candidates in LOGICAL_METRICS.values():
        batchable.extend(candidates)
    for batched_promql, by_name in batched_instant_queries(batchable):
        samples = [
            ({**labels, "__name__": name}, v)
            for name in by_name
            for labels, v in demo_series.get(name, [])
        ]
        if samples:
            t.add(q(batched_promql), vec(samples))

    # Range queries: synthesize utilization history on exactly the
    # requested (start, end, step) grid so the forecaster has real
    # traces to fit in demo mode. Registered BEFORE the generic /query
    # prefix — prefix routes match in insertion order and '…/query' is
    # a prefix of '…/query_range'.
    import math
    import urllib.parse as up

    def range_response(path: str) -> dict:
        query = up.parse_qs(up.urlparse(path).query)
        if "tensorcore_utilization" not in up.unquote(query["query"][0]):
            return {"status": "success", "data": {"resultType": "matrix", "result": []}}
        start = float(query["start"][0])
        end = float(query["end"][0])
        step = int(query["step"][0])
        result = []
        for i, node in enumerate(tpu_nodes[:16]):
            for chip in range(4):
                base = 0.4 + 0.1 * ((i + chip) % 3)
                values = []
                ts = start
                while ts <= end:
                    v = base + 0.25 * math.sin(ts / 600 + i + chip) + 0.15 * math.sin(
                        ts / 150 + chip
                    )
                    values.append([ts, f"{min(max(v, 0.0), 1.0):.4f}"])
                    ts += step
                result.append(
                    {
                        "metric": {"node": node, "accelerator_id": str(chip)},
                        "values": values,
                    }
                )
        return {"status": "success", "data": {"resultType": "matrix", "result": result}}

    t.add_prefix(
        "/api/v1/namespaces/monitoring/services/prometheus-k8s:9090/proxy/api/v1/query_range",
        range_response,
    )
    t.add_prefix(
        "/api/v1/namespaces/monitoring/services/prometheus-k8s:9090/proxy/api/v1/query",
        vec([]),
    )
    return t
