"""CLI entry: ``python -m headlamp_tpu.server``.

Modes:
- ``--demo [v5e4|v5p32|mixed|large]`` — fixture fleets, zero cluster.
- ``--apiserver URL``                 — real cluster (e.g. http://127.0.0.1:8001
  from ``kubectl proxy``).
- ``--in-cluster``                    — service-account auth inside a pod.

Read-tier roles (ADR-025):
- ``--replication-leader``            — publish every snapshot generation on
  the ``/replicate/bus`` endpoint (and run leader election on an
  in-process lease store, so the fencing/generation-band machinery is
  exercised even single-host).
- ``--replica URL``                   — no cluster access: consume the bus of
  the leader at URL and serve paints/push/ETags from applied records.

Multi-process serving (ADR-029):
- ``--workers N``                     — N single-threaded-serving worker
  processes accept on the port (SO_REUSEPORT or an inherited shared
  listener); the parent becomes the supervisor: it alone talks to the
  cluster and distributes each snapshot generation over a shared-memory
  segment, with the NDJSON bus on an internal port as the workers'
  counted fallback.
"""

from __future__ import annotations

import argparse

from ..transport.api_proxy import KubeTransport
from .app import DashboardApp, make_demo_transport


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(prog="headlamp_tpu.server")
    parser.add_argument("--demo", nargs="?", const="v5p32",
                        choices=["v5e4", "v5p32", "mixed", "large"], default=None)
    parser.add_argument("--apiserver", default=None,
                        help="kube-apiserver base URL (e.g. kubectl proxy)")
    parser.add_argument("--in-cluster", action="store_true")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8631)
    parser.add_argument(
        "--background-sync", type=float, metavar="SECONDS", default=None,
        help="sync the cluster state every N seconds off the request "
        "path (page views stop paying for syncs)",
    )
    parser.add_argument(
        "--active-pods-only", action="store_true",
        help="server-side fieldSelector dropping Succeeded/Failed pods "
        "from the reactive list (batch-heavy fleets)",
    )
    parser.add_argument(
        "--replication-leader", action="store_true",
        help="publish snapshot generations on /replicate/bus for read "
        "replicas (ADR-025)",
    )
    parser.add_argument(
        "--replica", metavar="LEADER_URL", default=None,
        help="run as a stateless read replica consuming the bus of the "
        "leader at LEADER_URL (no cluster access; ADR-025)",
    )
    parser.add_argument(
        "--workers", type=int, metavar="N", default=None,
        help="serve with N worker processes over a shared-memory "
        "snapshot plane; the parent becomes the supervisor/leader "
        "(ADR-029)",
    )
    args = parser.parse_args(argv)

    if args.replica:
        if args.demo or args.apiserver or args.in_cluster or args.replication_leader:
            parser.error("--replica excludes cluster modes and --replication-leader")
        if args.workers:
            parser.error("--replica excludes --workers (workers are replicas)")
        from ..replicate import BusConsumer, ReplicaApp, pool_fetch

        app = ReplicaApp()
        consumer = BusConsumer(app, pool_fetch(args.replica))
        consumer.start()
        server = app.serve(args.host, args.port)
        print(
            f"TPU dashboard REPLICA on http://{args.host}:{args.port}/tpu "
            f"(bus: {args.replica})"
        )
        try:
            server.serve_forever()
        except KeyboardInterrupt:  # analysis: disable=EXC001
            consumer.stop()
            server.shutdown()  # top-of-process Ctrl-C: clean stop IS the handling
        return

    if args.demo:
        transport = make_demo_transport(args.demo)
        mode = f"demo fleet '{args.demo}'"
    elif args.in_cluster:
        transport = KubeTransport.in_cluster()
        mode = "in-cluster"
    elif args.apiserver:
        transport = KubeTransport(args.apiserver)
        mode = args.apiserver
    else:
        parser.error("choose one of --demo, --apiserver URL, --in-cluster, "
                     "--replica URL")

    from ..context.sources import ACTIVE_PODS_FIELD_SELECTOR

    pod_field_selector = (
        ACTIVE_PODS_FIELD_SELECTOR if args.active_pods_only else None
    )

    if args.workers:
        if args.replication_leader:
            parser.error(
                "--workers excludes --replication-leader: the supervisor "
                "already publishes (its internal bus feeds the workers)"
            )
        from ..workers import run_supervisor

        def _leader_app() -> DashboardApp:
            return DashboardApp(transport, pod_field_selector=pod_field_selector)

        kwargs = {}
        if args.background_sync:
            kwargs["sync_interval_s"] = args.background_sync
        run_supervisor(
            _leader_app,
            host=args.host,
            port=args.port,
            workers=args.workers,
            **kwargs,
        )
        return

    app = DashboardApp(transport, pod_field_selector=pod_field_selector)
    elector = None
    if args.replication_leader:
        from ..replicate import (
            BusPublisher,
            LeaderElector,
            LeaseStore,
            generation_floor,
        )

        publisher = BusPublisher(note=f"{args.host}:{args.port}")
        app.replication = publisher

        def _elected(fencing: int) -> None:
            # Fencing token → generation band: everything this term
            # publishes outranks every earlier term (ADR-025).
            publisher.set_fencing(fencing)
            app._ctx.advance_generation_floor(generation_floor(fencing))

        elector = LeaderElector(
            LeaseStore(), f"{args.host}:{args.port}", on_elected=_elected
        )
        elector.tick()
        elector.start()
        mode += ", replication leader"
    if args.background_sync:
        app.start_background_sync(args.background_sync)
    server = app.serve(args.host, args.port)
    print(f"TPU dashboard on http://{args.host}:{args.port}/tpu ({mode})")
    try:
        server.serve_forever()
    except KeyboardInterrupt:  # analysis: disable=EXC001
        if elector is not None:
            elector.stop()
            elector.resign()
        server.shutdown()  # top-of-process Ctrl-C: clean stop IS the handling


if __name__ == "__main__":
    main()
