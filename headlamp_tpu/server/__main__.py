"""CLI entry: ``python -m headlamp_tpu.server``.

Modes:
- ``--demo [v5e4|v5p32|mixed|large]`` — fixture fleets, zero cluster.
- ``--apiserver URL``                 — real cluster (e.g. http://127.0.0.1:8001
  from ``kubectl proxy``).
- ``--in-cluster``                    — service-account auth inside a pod.
"""

from __future__ import annotations

import argparse

from ..transport.api_proxy import KubeTransport
from .app import DashboardApp, make_demo_transport


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(prog="headlamp_tpu.server")
    parser.add_argument("--demo", nargs="?", const="v5p32",
                        choices=["v5e4", "v5p32", "mixed", "large"], default=None)
    parser.add_argument("--apiserver", default=None,
                        help="kube-apiserver base URL (e.g. kubectl proxy)")
    parser.add_argument("--in-cluster", action="store_true")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8631)
    args = parser.parse_args(argv)

    if args.demo:
        transport = make_demo_transport(args.demo)
        mode = f"demo fleet '{args.demo}'"
    elif args.in_cluster:
        transport = KubeTransport.in_cluster()
        mode = "in-cluster"
    elif args.apiserver:
        transport = KubeTransport(args.apiserver)
        mode = args.apiserver
    else:
        parser.error("choose one of --demo, --apiserver URL, --in-cluster")

    app = DashboardApp(transport)
    server = app.serve(args.host, args.port)
    print(f"TPU dashboard on http://{args.host}:{args.port}/tpu ({mode})")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.shutdown()


if __name__ == "__main__":
    main()
