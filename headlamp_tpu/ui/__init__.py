"""UI kit — element tree and common components.

The reference renders through Headlamp's CommonComponents
(`SectionBox`, `SimpleTable`, `NameValueTable`, `StatusLabel`,
`PercentageBar`, `Loader`, `SectionHeader` — e.g.
`/root/reference/src/components/OverviewPage.tsx:8-16`). This package is
the framework's own implementation of that kit over a minimal immutable
element tree that renders to HTML (dashboard server) and plain text
(CLI/tests). Pages build trees; renderers are separate — the same
separation React gives the reference.
"""

from .vdom import Element, find_all, h, render_html, render_text, text_content
from .fragment import (
    FragmentBoundary,
    FragmentCache,
    FragmentPaint,
    fragment,
    set_active_fragments,
)
from .components import (
    BAR_CRIT_PCT,
    BAR_WARN_PCT,
    EmptyContent,
    ErrorBox,
    Loader,
    NameValueTable,
    PercentageBar,
    SectionBox,
    SectionHeader,
    SimpleTable,
    StatusLabel,
    UtilizationBar,
)

__all__ = [
    "Element",
    "h",
    "render_html",
    "render_text",
    "text_content",
    "find_all",
    "FragmentBoundary",
    "FragmentCache",
    "FragmentPaint",
    "fragment",
    "set_active_fragments",
    "BAR_CRIT_PCT",
    "BAR_WARN_PCT",
    "EmptyContent",
    "ErrorBox",
    "Loader",
    "NameValueTable",
    "PercentageBar",
    "SectionBox",
    "SectionHeader",
    "SimpleTable",
    "StatusLabel",
    "UtilizationBar",
]
