"""Common components — the framework's CommonComponents kit.

Semantics mirror the Headlamp kit the reference composes
(`/root/reference/src/components/OverviewPage.tsx:8-16` imports
SectionBox, SimpleTable, NameValueTable, StatusLabel, Loader,
PercentageBar, SectionHeader). Each returns an :class:`Element`;
``class_`` names (``hl-*``) are the stable hooks tests and the
stylesheet key off.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Mapping, Sequence

from .vdom import Element, h

# Column spec: {"label": str, "getter": callable(row) -> Child} or
# {"label": str, "key": str} for dict rows — SimpleTable's two forms.
Column = Mapping[str, Any]


def SectionBox(title: str | None, *children: Any, class_: str = "") -> Element:
    """Titled section container (SectionBox + implicit SectionHeader)."""
    cls = f"hl-section {class_}".strip()
    return h(
        "section",
        {"class_": cls},
        h("h2", {"class_": "hl-section-title"}, title) if title else None,
        *children,
    )


def SectionHeader(title: str, *actions: Any) -> Element:
    return h(
        "header",
        {"class_": "hl-section-header"},
        h("h2", None, title),
        h("div", {"class_": "hl-actions"}, *actions) if actions else None,
    )


def SimpleTable(
    columns: Sequence[Column],
    data: Iterable[Any],
    *,
    empty_message: str = "No data",
    row_key: Callable[[Any], str] | None = None,
    row_salt: Callable[[Any], Any] | None = None,
) -> Element:
    """Column-spec table (`SimpleTable` semantics: columns with label +
    getter, empty state built in).

    With ``row_key``/``row_salt`` each ``<tr>`` becomes a
    :class:`~headlamp_tpu.ui.fragment.FragmentBoundary` (ADR-027): the
    key must speak the differ's row vocabulary and the salt must cover
    every cell input, so an unchanged row splices from cached bytes
    instead of re-running its getters."""
    rows = list(data)
    if not rows:
        return h("p", {"class_": "hl-empty"}, empty_message)

    def cell(col: Column, row: Any) -> Any:
        getter: Callable[[Any], Any] | None = col.get("getter")
        if getter is not None:
            return getter(row)
        key = col.get("key")
        if isinstance(row, Mapping) and key is not None:
            return row.get(key, "")
        return ""

    def tr(row: Any) -> Any:
        return h("tr", None, [h("td", None, cell(c, row)) for c in columns])

    if row_key is not None and row_salt is not None:
        from .fragment import fragment

        body = [
            fragment(row_key(row), row_salt(row), lambda row=row: tr(row))
            for row in rows
        ]
    else:
        body = [tr(row) for row in rows]

    return h(
        "table",
        {"class_": "hl-table"},
        h("tr", None, [h("th", None, c["label"]) for c in columns]),
        body,
    )


def NameValueTable(rows: Sequence[tuple[Any, Any]]) -> Element:
    """Two-column name/value layout (detail cards)."""
    return h(
        "dl",
        {"class_": "hl-namevalue"},
        [
            (h("dt", None, name), h("dd", None, value))
            for name, value in rows
        ],
    )


#: status -> css class; mirrors Headlamp's StatusLabel palette.
_STATUS_CLASSES = {"success": "ok", "warning": "warn", "error": "err", "": "neutral"}


def StatusLabel(status: str, text: Any) -> Element:
    """Colored status chip: status in {'success','warning','error',''}."""
    cls = _STATUS_CLASSES.get(status, "neutral")
    return h("span", {"class_": f"hl-status hl-status-{cls}", "data-status": status}, text)


def PercentageBar(parts: Sequence[tuple[str, float]], *, total: float | None = None) -> Element:
    """Stacked distribution bar: [(label, value)]. Renders each part with
    a width percentage and a legend (the GPU-type distribution bar,
    `OverviewPage.tsx:275-312`)."""
    values = [(str(label), max(0.0, float(v))) for label, v in parts]
    denom = total if total and total > 0 else sum(v for _, v in values)
    denom = denom or 1.0
    return h(
        "div",
        {"class_": "hl-pctbar"},
        h(
            "div",
            {"class_": "hl-pctbar-track"},
            [
                h(
                    "div",
                    {
                        "class_": "hl-pctbar-part",
                        "style": f"width:{v / denom * 100:.1f}%",
                        "title": f"{label}: {v:g}",
                    },
                )
                for label, v in values
                if v > 0
            ],
        ),
        h(
            "div",
            {"class_": "hl-pctbar-legend"},
            [h("span", None, f"{label}: {v:g}") for label, v in values],
        ),
    )


#: Allocation-bar thresholds shared framework-wide — the reference uses
#: 70/90 in three places (`NodesPage.tsx:38`, `MetricsPage.tsx:52-53`,
#: `NodeDetailSection.tsx:90-91`); here they live once.
BAR_WARN_PCT = 70
BAR_CRIT_PCT = 90


def UtilizationBar(used: float, capacity: float, *, unit: str = "") -> Element:
    """Single-value meter with 70/90% warn/crit coloring."""
    pct = 0.0 if capacity <= 0 else min(100.0, used / capacity * 100)
    level = "err" if pct >= BAR_CRIT_PCT else "warn" if pct >= BAR_WARN_PCT else "ok"
    label = f"{used:g}/{capacity:g}{(' ' + unit) if unit else ''} ({pct:.0f}%)"
    return h(
        "div",
        {"class_": f"hl-utilbar hl-utilbar-{level}", "data-pct": f"{pct:.0f}"},
        h("div", {"class_": "hl-utilbar-fill", "style": f"width:{pct:.1f}%"}),
        h("span", {"class_": "hl-utilbar-label"}, label),
    )


def BudgetBar(remaining_ratio: float) -> Element:
    """Error-budget meter for the SLO status page: shows the UNSPENT
    fraction, colored by how little is left — the inverse reading of
    UtilizationBar, on the same shared 70/90 thresholds (err at ≤10%
    remaining, warn at ≤30%)."""
    pct = max(0.0, min(1.0, float(remaining_ratio))) * 100
    level = (
        "err"
        if pct <= 100 - BAR_CRIT_PCT
        else "warn" if pct <= 100 - BAR_WARN_PCT else "ok"
    )
    return h(
        "div",
        {"class_": f"hl-budgetbar hl-utilbar hl-utilbar-{level}", "data-pct": f"{pct:.0f}"},
        h("div", {"class_": "hl-utilbar-fill", "style": f"width:{pct:.1f}%"}),
        h("span", {"class_": "hl-utilbar-label"}, f"{pct:.1f}% budget left"),
    )


def Loader(title: str = "Loading…") -> Element:
    return h("div", {"class_": "hl-loader", "role": "progressbar"}, title)


def EmptyContent(*children: Any) -> Element:
    return h("div", {"class_": "hl-empty-content"}, *children)


def ErrorBox(message: str) -> Element:
    """The aggregated-error banner every page shows when
    ``snapshot.error`` is set (`OverviewPage.tsx:162-168`)."""
    return h("div", {"class_": "hl-error", "role": "alert"}, "Error: ", message)
