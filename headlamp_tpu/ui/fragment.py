"""Incremental fragment rendering (ADR-027).

The ADR-021 differ already knows exactly which keyed rows and cells
changed per sync generation, and ADR-026 gives every drill-down region
a stable path — but until this layer the renderer rebuilt and
re-serialized every subtree on every paint. Here pages mark their
row/region/cell-group subtrees as :class:`FragmentBoundary` nodes (key
= the differ's row key or the viewport region path, salt = every
render-relevant input beyond the key), and the server paints through a
:class:`FragmentPaint` context over a bounded, counted LRU
(:class:`FragmentCache`):

* **resolve phase** (billed to ``page.component``): every boundary
  whose bytes are not cached for the current ``(epoch, degraded,
  salt)`` is rendered ONCE into the cache — O(changed), because the
  push pipeline evicted exactly the keys the differ saw change.
* **splice phase** (billed to ``fragment.splice``): the final byte
  assembly appends cached fragment strings instead of descending the
  subtrees.

Invalidation is push-driven: ``PushPipeline.on_snapshot`` hands the
differ's per-generation change set to :meth:`FragmentCache.invalidate`
at diff time — no second diff pass on the request path. The salt is
the correctness backstop: fragment bytes must be a pure function of
``(key, salt)`` (boundary-placement rule #1 in ADR-027), so even an
un-evicted entry can never serve stale bytes — a salt mismatch is a
miss, and the entry is replaced in place.

Byte-identity contract: a paint through this layer is byte-identical
to plain :func:`~headlamp_tpu.ui.vdom.render_html` over the same tree
(which descends boundaries transparently) — pinned across recorded
churn by the ADR-018 replay tests.
"""

from __future__ import annotations

import threading
import weakref
from collections import OrderedDict
from typing import Any, Callable, Iterable

from ..obs.metrics import registry as _metrics_registry
from .vdom import BoundaryNode, Child, Element, _render_html_into

#: LRU entry bound. At the 1024-node fixture the hot set is ~1k node
#: rows + ~1k pod rows + ~4k chip/forecast rows + O(regions) + O(10)
#: section groups, so the default holds a full large-fleet working set
#: without eviction churn while still bounding a hostile key space.
DEFAULT_MAX_ENTRIES = 8192

_HITS = _metrics_registry.counter(
    "headlamp_tpu_render_fragment_hits_total",
    "Fragment-cache hits: boundary subtrees spliced from cached bytes "
    "instead of re-rendered (ADR-027).",
)
_MISSES = _metrics_registry.counter(
    "headlamp_tpu_render_fragment_misses_total",
    "Fragment-cache misses: boundary subtrees (re-)rendered because no "
    "entry matched the (epoch, degraded, salt) invariants.",
)
_EVICTIONS = _metrics_registry.counter(
    "headlamp_tpu_render_fragment_evictions_total",
    "Fragment-cache entries dropped: LRU pressure plus differ-driven "
    "invalidations (changed/removed keys evicted at diff time).",
)

#: The serving cache, for the memory gauge — same weakref discipline as
#: the push clients gauge: tests/bench build many apps per process and
#: the gauge must follow the live one.
_ACTIVE: "weakref.ref[FragmentCache] | None" = None


def set_active_fragments(cache: "FragmentCache | None") -> None:
    global _ACTIVE
    _ACTIVE = weakref.ref(cache) if cache is not None else None


def _bytes_sample() -> float | None:
    cache = _ACTIVE() if _ACTIVE is not None else None
    return float(cache.bytes) if cache is not None else None


_metrics_registry.gauge_fn(
    "headlamp_tpu_render_fragment_cache_bytes",
    "UTF-8 bytes of rendered HTML held by the serving fragment cache.",
    _bytes_sample,
)


class FragmentBoundary(BoundaryNode):
    """A lazy, cacheable subtree.

    ``key`` speaks the differ's vocabulary (row key, region path, or a
    ``cells:``-prefixed group name) so the push pipeline's change set
    maps straight onto cache evictions. ``salt`` must capture EVERY
    render-relevant input that is not implied by the key — including
    request-time strings like formatted ages — because cached bytes
    are reused whenever the salt matches. ``build`` runs only when the
    bytes are not already cached (and at most once per node)."""

    __slots__ = ("key", "salt", "_build", "_built", "_html")

    def __init__(self, key: str, salt: Any, build: Callable[[], Child]) -> None:
        self.key = key
        self.salt = salt
        self._build = build
        self._built: Child = None
        self._html: str | None = None

    def built(self) -> Child:
        if self._built is None:
            self._built = self._build()
        return self._built


def fragment(key: str, salt: Any, build: Callable[[], Child]) -> FragmentBoundary:
    """Hyperscript-style constructor pages use to mark a boundary."""
    return FragmentBoundary(key, salt, build)


class _Entry:
    __slots__ = ("salt", "epoch", "degraded", "generation", "html", "nbytes")

    def __init__(
        self,
        salt: Any,
        epoch: int,
        degraded: bool,
        generation: int,
        html: str,
    ) -> None:
        self.salt = salt
        self.epoch = epoch
        self.degraded = degraded
        self.generation = generation
        self.html = html
        self.nbytes = len(html.encode("utf-8"))


class FragmentCache:
    """Bounded, counted LRU of rendered fragment bytes.

    Entries key on ``(page, key)`` and carry the ADR-021 ETag
    invariants — ``(generation, cache-epoch, degraded)`` — plus the
    salt. A lookup hits only when epoch, degraded flag, AND salt all
    match; a hit re-stamps the entry's generation (the entry is proven
    current for the paint's generation). Every miss and every eviction
    is counted — never silent — and byte totals feed the
    ``headlamp_tpu_render_fragment_cache_bytes`` gauge."""

    def __init__(self, max_entries: int = DEFAULT_MAX_ENTRIES) -> None:
        self.max_entries = max(1, int(max_entries))
        self._lock = threading.Lock()
        self._entries: OrderedDict[tuple[str, str], _Entry] = OrderedDict()
        #: key -> pages holding it, so a differ key invalidates every
        #: page namespace it renders under (node rows appear on both
        #: /tpu/nodes and /tpu/fleet) in O(occurrences).
        self._pages_of: dict[str, set[str]] = {}
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(
        self,
        page: str,
        key: str,
        salt: Any,
        *,
        generation: int,
        epoch: int,
        degraded: bool,
    ) -> str | None:
        full = (page, key)
        with self._lock:
            entry = self._entries.get(full)
            if (
                entry is not None
                and entry.epoch == epoch
                and entry.degraded == degraded
                and entry.salt == salt
            ):
                self._entries.move_to_end(full)
                entry.generation = generation
                self.hits += 1
                _HITS.inc()
                return entry.html
            self.misses += 1
            _MISSES.inc()
            return None

    def put(
        self,
        page: str,
        key: str,
        salt: Any,
        html: str,
        *,
        generation: int,
        epoch: int,
        degraded: bool,
    ) -> None:
        full = (page, key)
        entry = _Entry(salt, epoch, degraded, generation, html)
        with self._lock:
            old = self._entries.pop(full, None)
            if old is not None:
                self.bytes -= old.nbytes
            self._entries[full] = entry
            self.bytes += entry.nbytes
            self._pages_of.setdefault(key, set()).add(page)
            while len(self._entries) > self.max_entries:
                (old_page, old_key), dropped = self._entries.popitem(last=False)
                self.bytes -= dropped.nbytes
                self._discard_index(old_page, old_key)
                self.evictions += 1
                _EVICTIONS.inc()

    def _discard_index(self, page: str, key: str) -> None:
        pages = self._pages_of.get(key)
        if pages is not None:
            pages.discard(page)
            if not pages:
                del self._pages_of[key]

    def invalidate(self, keys: Iterable[str]) -> int:
        """Differ-driven eviction (ADR-027): drop every cached fragment
        whose key the differ saw change/disappear this generation —
        across ALL page namespaces holding it. Runs on the sync thread
        at diff time; O(changed keys), never a tree walk. Returns the
        number of entries dropped (each counted as an eviction)."""
        dropped = 0
        with self._lock:
            for key in keys:
                pages = self._pages_of.pop(key, None)
                if not pages:
                    continue
                for page in pages:
                    entry = self._entries.pop((page, key), None)
                    if entry is not None:
                        self.bytes -= entry.nbytes
                        dropped += 1
            if dropped:
                self.evictions += dropped
                _EVICTIONS.inc(dropped)
        return dropped

    def clear(self) -> int:
        with self._lock:
            dropped = len(self._entries)
            self._entries.clear()
            self._pages_of.clear()
            self.bytes = 0
            if dropped:
                self.evictions += dropped
                _EVICTIONS.inc(dropped)
            return dropped

    def __len__(self) -> int:
        return len(self._entries)

    def counters(self) -> dict[str, int]:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
        }

    def snapshot(self) -> dict[str, Any]:
        """The /healthz ``runtime.render`` block."""
        hits, misses = self.hits, self.misses
        total = hits + misses
        return {
            "entries": len(self._entries),
            "max_entries": self.max_entries,
            "bytes": self.bytes,
            "hits": hits,
            "misses": misses,
            "evictions": self.evictions,
            "hit_rate": round(hits / total, 4) if total else None,
        }


class FragmentPaint:
    """One paint's fragment context: the cache plus the ETag invariants
    the entries key on. ``prerender`` is the page.component phase
    (renders stale boundaries into the cache); ``splice`` is the
    fragment.splice phase (assembles final bytes, appending cached
    fragments instead of descending)."""

    __slots__ = ("cache", "page", "generation", "epoch", "degraded", "rendered", "spliced")

    def __init__(
        self,
        cache: FragmentCache,
        *,
        page: str,
        generation: int,
        epoch: int,
        degraded: bool,
    ) -> None:
        self.cache = cache
        self.page = page
        self.generation = generation
        self.epoch = epoch
        self.degraded = degraded
        self.rendered = 0
        self.spliced = 0

    def _resolve(self, node: BoundaryNode) -> str:
        assert isinstance(node, FragmentBoundary)
        # Per-paint memo on the node itself: prerender resolves, splice
        # reuses — one cache lookup per boundary per paint, so the
        # hit/miss counters mean what they say.
        html = node._html
        if html is not None:
            return html
        html = self.cache.get(
            self.page,
            node.key,
            node.salt,
            generation=self.generation,
            epoch=self.epoch,
            degraded=self.degraded,
        )
        if html is None:
            buf: list[str] = []
            _render_html_into(node.built(), buf, self._resolve)
            html = "".join(buf)
            self.cache.put(
                self.page,
                node.key,
                node.salt,
                html,
                generation=self.generation,
                epoch=self.epoch,
                degraded=self.degraded,
            )
            self.rendered += 1
        else:
            self.spliced += 1
        node._html = html
        return html

    def prerender(self, node: Child) -> None:
        """Render every stale boundary in ``node`` into the cache (the
        changed-fragment re-render the page.component span bills).
        Boundaries inside a cached fragment are never visited — their
        bytes are already inside the parent's entry."""
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, FragmentBoundary):
                self._resolve(n)
            elif isinstance(n, Element):
                stack.extend(n.children)

    def splice(self, node: Child) -> str:
        """Assemble the full page bytes, splicing cached fragments."""
        out: list[str] = []
        _render_html_into(node, out, self._resolve)
        return "".join(out)


__all__ = [
    "DEFAULT_MAX_ENTRIES",
    "FragmentBoundary",
    "FragmentCache",
    "FragmentPaint",
    "fragment",
    "set_active_fragments",
]
