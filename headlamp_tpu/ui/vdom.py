"""Minimal immutable element tree with HTML and text renderers.

The structural role React's element tree plays for the reference: pages
return trees; tests assert on structure/text (the reference's
testing-library ``getByText`` discipline, SURVEY.md §4 tier 3); the
server renders HTML. No diffing — snapshots re-render whole pages, which
at BASELINE scale (256 nodes) is cheap and keeps rendering a pure
function of the snapshot.
"""

from __future__ import annotations

import html
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator

Child = Any  # Element | str | int | float | None (None children are dropped)


@dataclass(frozen=True, slots=True)
class Element:
    tag: str
    props: dict[str, Any] = field(default_factory=dict)
    children: tuple[Any, ...] = ()


class BoundaryNode:
    """Marker base for lazy subtree nodes (``ui.fragment`` — ADR-027).

    A boundary stands in for a subtree that may be served from the
    fragment cache instead of being rebuilt. Every walker in this
    module treats boundaries TRANSPARENTLY by descending through
    :meth:`built`, so text projection, assertions, and the plain
    ``render_html`` oracle see exactly the tree the boundary would
    build — only the incremental renderer (which passes a ``resolve``
    hook) ever skips the descent."""

    __slots__ = ()

    def built(self) -> "Child":
        raise NotImplementedError


def h(tag: str, props: dict[str, Any] | None = None, *children: Child) -> Element:
    """Hyperscript constructor. Nested lists/tuples and None children are
    flattened/dropped so callers can build conditionally:
    ``h('div', None, [rows], error and error_box(error))``."""
    flat: list[Any] = []

    def add(c: Any) -> None:
        if c is None or c is False:
            return
        if isinstance(c, (list, tuple)) and not isinstance(c, Element):
            for item in c:
                add(item)
            return
        flat.append(c)

    for c in children:
        add(c)
    return Element(tag=tag, props=dict(props or {}), children=tuple(flat))


# ---------------------------------------------------------------------------
# Renderers
# ---------------------------------------------------------------------------

_VOID_TAGS = {"br", "hr", "img", "input", "meta", "link"}


def render_html(node: Child) -> str:
    """Escaped HTML. Props: ``class_`` -> class; ``data`` values are
    str()ed; callables/None skipped.

    Single-pass writer into one buffer: the recursive-concat version
    copied every subtree's string once per ancestor level (O(n·depth)
    bytes moved per page; thousands of nodes at fleet scale)."""
    out: list[str] = []
    _render_html_into(node, out)
    return "".join(out)


def _render_html_into(
    node: Child,
    out: list[str],
    resolve: "Callable[[BoundaryNode], str] | None" = None,
) -> None:
    if node is None:
        return
    if isinstance(node, BoundaryNode):
        # ``resolve`` is the fragment-cache hook (ADR-027): it returns
        # the boundary's bytes (cached or freshly rendered). Without
        # one, descend — plain render_html IS the non-incremental
        # oracle the byte-identity tests pin against.
        if resolve is not None:
            out.append(resolve(node))
        else:
            _render_html_into(node.built(), out)
        return
    if not isinstance(node, Element):
        out.append(html.escape(str(node)))
        return
    attrs = []
    for key, value in node.props.items():
        if value is None or callable(value):
            continue
        name = "class" if key == "class_" else key
        if value is True:
            attrs.append(name)
        else:
            attrs.append(f'{name}="{html.escape(str(value), quote=True)}"')
    attr_str = (" " + " ".join(attrs)) if attrs else ""
    if node.tag in _VOID_TAGS:
        out.append(f"<{node.tag}{attr_str}/>")
        return
    out.append(f"<{node.tag}{attr_str}>")
    for c in node.children:
        _render_html_into(c, out, resolve)
    out.append(f"</{node.tag}>")


_BLOCK_TAGS = {
    "div", "p", "section", "table", "tr", "ul", "ol", "li",
    "h1", "h2", "h3", "h4", "header", "footer", "dl",
}


def render_text(node: Child) -> str:
    """Plain-text projection: block tags break lines, table cells are
    tab-separated. What the CLI prints and what tests grep."""
    out: list[str] = []

    def walk(n: Child) -> None:
        if n is None:
            return
        if isinstance(n, BoundaryNode):
            walk(n.built())
            return
        if not isinstance(n, Element):
            out.append(str(n))
            return
        if n.tag in ("td", "th") and out and out[-1] not in ("\n", "\t"):
            out.append("\t")
        for c in n.children:
            walk(c)
        if n.tag == "dt":
            # Name/value pairs: name<TAB>value, one pair per line (the
            # dd below closes the line via _BLOCK_TAGS).
            out.append("\t")
        elif n.tag == "dd":
            out.append("\n")
        elif n.tag in _BLOCK_TAGS:
            out.append("\n")

    walk(node)
    text = "".join(out)
    lines = [line.strip("\t ") for line in text.split("\n")]
    return "\n".join(line for line in lines if line)


def text_content(node: Child) -> str:
    """All text, single-spaced — the assertion helper
    (testing-library's textContent analogue)."""
    parts: list[str] = []

    def walk(n: Child) -> None:
        if n is None:
            return
        if isinstance(n, BoundaryNode):
            walk(n.built())
            return
        if not isinstance(n, Element):
            parts.append(str(n))
            return
        for c in n.children:
            walk(c)

    walk(node)
    return " ".join(" ".join(parts).split())


def find_all(node: Child, predicate: Callable[[Element], bool]) -> list[Element]:
    """Depth-first search over the tree (querySelector analogue)."""
    found: list[Element] = []

    def walk(n: Child) -> None:
        if isinstance(n, BoundaryNode):
            walk(n.built())
            return
        if not isinstance(n, Element):
            return
        if predicate(n):
            found.append(n)
        for c in n.children:
            walk(c)

    walk(node)
    return found


def iter_elements(node: Child) -> Iterator[Element]:
    if isinstance(node, BoundaryNode):
        yield from iter_elements(node.built())
    elif isinstance(node, Element):
        yield node
        for c in node.children:
            yield from iter_elements(c)
