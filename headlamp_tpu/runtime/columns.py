"""Columnar fleet ⇄ flat bytes (ADR-029 column layout export).

The ADR-012 :class:`~headlamp_tpu.analytics.encode.FleetArrays` columns
are already contiguous fixed-dtype numpy arrays — the exact shape a
shared-memory segment wants. :func:`pack_fleet` serializes one
FleetArrays to a self-describing byte blob (magic + JSON table of
contents + 8-aligned raw column bytes); :func:`unpack_fleet` rebuilds
it with ``np.frombuffer`` VIEWS over the source buffer — zero copy, so
a worker attaching a published segment pays parsing of a ~200-byte toc,
not a per-column copy, and never re-runs ``encode_fleet``'s Python
loop over the fleet.

The blob is versioned by its magic: a reader that sees a different
magic refuses the blob outright (the ADR-029 version gate at the
column layer), mirroring the bus codec's ``BUS_VERSION`` stance —
never half-decode a foreign layout.

Mutability contract: ``unpack_fleet`` views are as writable as the
buffer they wrap. Callers handing out views over shared memory MUST
pass an immutable snapshot (``bytes``) or a read-only memoryview —
the seqlock in ``workers/shm.py`` copies the payload out of the mmap
before unpacking for exactly this reason.
"""

from __future__ import annotations

import json
import struct
from typing import TYPE_CHECKING

import numpy as np

if TYPE_CHECKING:  # pragma: no cover - typing only
    from ..analytics.encode import FleetArrays

#: Layout version rides in the magic itself — bump the trailing digit
#: for incompatible changes and old readers refuse by magic mismatch.
COLUMNS_MAGIC = b"HLTPCOL1"

#: Array fields serialized, in a FIXED order (the toc repeats the
#: names, so the order is a determinism nicety, not a decode input).
ARRAY_FIELDS: tuple[str, ...] = (
    "node_capacity",
    "node_allocatable",
    "node_ready",
    "node_generation",
    "node_valid",
    "pod_request",
    "pod_phase",
    "pod_node_idx",
    "pod_valid",
)

_LEN = struct.Struct("<I")


def _pad8(n: int) -> int:
    return (-n) % 8


def pack_fleet(fleet: "FleetArrays") -> bytes:
    """One FleetArrays → self-describing bytes. Deterministic for a
    given fleet (canonical JSON toc, fixed field order, zero padding),
    so two packs of the same arrays are byte-identical — the same
    property the bus codec pins for NDJSON lines."""
    parts: list[bytes] = []
    columns: list[list[object]] = []
    offset = 0
    for name in ARRAY_FIELDS:
        arr = np.ascontiguousarray(getattr(fleet, name))
        raw = arr.tobytes()
        columns.append([name, arr.dtype.str, int(arr.shape[0]), offset])
        parts.append(raw)
        pad = _pad8(len(raw))
        if pad:
            parts.append(b"\x00" * pad)
        offset += len(raw) + pad
    toc = json.dumps(
        {
            "n_nodes": int(fleet.n_nodes),
            "n_pods": int(fleet.n_pods),
            "node_names": list(fleet.node_names),
            "columns": columns,
        },
        sort_keys=True,
        separators=(",", ":"),
    ).encode("utf-8")
    head = COLUMNS_MAGIC + _LEN.pack(len(toc))
    lead = len(head) + len(toc)
    return head + toc + b"\x00" * _pad8(lead) + b"".join(parts)


def unpack_fleet(buf: bytes | memoryview) -> "FleetArrays":
    """Bytes → FleetArrays whose columns are ``frombuffer`` views over
    ``buf`` (zero copy). Raises ``ValueError`` on a foreign magic or a
    truncated blob — a corrupt segment must surface as an exception the
    worker's fallback ladder can count, never as garbage arrays."""
    from ..analytics.encode import FleetArrays

    view = memoryview(buf)
    if len(view) < len(COLUMNS_MAGIC) + _LEN.size:
        raise ValueError("column blob truncated before header")
    if bytes(view[: len(COLUMNS_MAGIC)]) != COLUMNS_MAGIC:
        raise ValueError(
            f"column blob magic mismatch (expected {COLUMNS_MAGIC!r})"
        )
    (toc_len,) = _LEN.unpack_from(view, len(COLUMNS_MAGIC))
    toc_start = len(COLUMNS_MAGIC) + _LEN.size
    if len(view) < toc_start + toc_len:
        raise ValueError("column blob truncated inside toc")
    toc = json.loads(bytes(view[toc_start : toc_start + toc_len]))
    lead = toc_start + toc_len
    data_start = lead + _pad8(lead)
    arrays: dict[str, np.ndarray] = {}
    for name, dtype, length, offset in toc["columns"]:
        if name not in ARRAY_FIELDS:
            continue  # forward-compat: unknown columns skipped, not fatal
        dt = np.dtype(dtype)
        end = data_start + offset + length * dt.itemsize
        if end > len(view):
            raise ValueError(f"column blob truncated inside column {name!r}")
        arrays[name] = np.frombuffer(
            view, dtype=dt, count=length, offset=data_start + offset
        )
    missing = [name for name in ARRAY_FIELDS if name not in arrays]
    if missing:
        raise ValueError(f"column blob missing columns: {missing}")
    return FleetArrays(
        n_nodes=int(toc["n_nodes"]),
        n_pods=int(toc["n_pods"]),
        node_names=[str(n) for n in toc["node_names"]],
        **arrays,
    )


__all__ = ["ARRAY_FIELDS", "COLUMNS_MAGIC", "pack_fleet", "unpack_fleet"]
