"""Request-scoped device→host transfer coalescing.

Every blocking ``jax.device_get`` the serving path pays goes through
this module's :func:`device_get` funnel. That buys two things:

1. **Counting.** ``transfer_stats`` records how many blocking fetches
   the process (and the currently active batch) has paid — the number
   behind bench.py's ``device_gets_per_request`` and the /healthz
   transfer block. Over a tunneled device each blocking fetch costs a
   full tunnel RTT (~89 ms floor, BENCH_r05), so the count IS the
   latency model; it must be observable, not assumed.

2. **Coalescing.** A :class:`TransferBatch` installed for the scope of
   one HTTP request (``DashboardApp.handle``) lets independent stages —
   the XLA fleet rollup, the forecast's (predictions, fit_mse) pair, a
   sharded-mesh rollup — *register* dispatched device arrays instead of
   each blocking on its own fetch. The first stage that needs a value
   flushes everything registered so far in ONE ``jax.device_get``: all
   pending trees ride one tunnel round-trip. JAX dispatch is async, so
   registration costs nothing device-side; only the flush blocks.

The batch is carried in a :mod:`contextvars` ContextVar, so under
``ThreadingHTTPServer`` each request thread sees only its own batch and
code below the app layer (``fleet_jax.rollup_to_dict``,
``models.service``) needs no plumbed-through argument. No batch active
(CLI renders, tests, benches calling the kernels directly) means
:func:`fetch` degrades to a plain counted ``jax.device_get`` — the
pre-coalescer behavior, one fetch per call site.

jax is imported lazily inside the fetch paths only: a jax-less host can
import this module (the server does unconditionally) and never touch it.
"""

from __future__ import annotations

import contextlib
import threading
from contextvars import ContextVar
from typing import Any, Callable, Iterator

from ..obs.metrics import registry as _metrics_registry
from ..obs.trace import span as _span


class TransferStats:
    """Monotonic process-wide transfer counters — since ADR-013 a view
    over the obs metric registry: the storage lives in registry
    counters so /metricsz scrapes the same numbers /healthz reports,
    and the two surfaces can never disagree. The property readers keep
    the pre-registry attribute API (bench deltas, tests)."""

    def __init__(self) -> None:
        self._blocking = _metrics_registry.counter(
            "headlamp_tpu_transfer_blocking_gets_total",
            "Blocking device_get round-trips paid by the process "
            "(each costs a full tunnel RTT on a tunneled device)",
        )
        self._coalesced = _metrics_registry.counter(
            "headlamp_tpu_transfer_coalesced_trees_total",
            "Trees that rode a flush alongside at least one other tree "
            "- round-trips the coalescer saved",
        )

    @property
    def blocking_gets(self) -> int:
        return int(self._blocking.value)

    @property
    def coalesced_trees(self) -> int:
        return int(self._coalesced.value)

    def record_blocking_get(self) -> None:
        self._blocking.inc()

    def record_coalesced(self, trees: int) -> None:
        self._coalesced.inc(trees)

    def snapshot(self) -> dict[str, int]:
        return {
            "blocking_gets": self.blocking_gets,
            "coalesced_trees": self.coalesced_trees,
        }

    def counters(self) -> dict[str, int]:
        """Monotone counters only — the flight recorder's per-request
        delta view (here identical to snapshot; the shared name is the
        contract across runtime/transport components)."""
        return self.snapshot()


transfer_stats = TransferStats()

_ACTIVE: ContextVar["TransferBatch | None"] = ContextVar(
    "hl_tpu_transfer_batch", default=None
)


def active_batch() -> "TransferBatch | None":
    return _ACTIVE.get()


def _counted_device_get(tree: Any, batch: "TransferBatch | None") -> Any:
    import jax

    transfer_stats.record_blocking_get()
    if batch is not None:
        batch.blocking_gets += 1
    values = jax.device_get(tree)
    _note_transfer_bytes(values)
    return values


def _tree_nbytes(values: Any) -> int:
    """Payload bytes of a fetched host tree: sum of leaf ``nbytes``
    (numpy arrays), with plain Python scalars costed at 8 — the
    device-side float64/int64 a bare scalar fetch materializes."""
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(values)
    except Exception:  # noqa: BLE001 — jax-less caller: nothing fetched
        return 0
    total = 0
    for leaf in leaves:
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is None:
            nbytes = 8 if isinstance(leaf, (int, float, complex)) else 0
        total += int(nbytes)
    return total


def _note_transfer_bytes(values: Any) -> None:
    """Dual-account the fetched payload into the ADR-019 JAX cost
    ledger — the SAME transition that just incremented
    ``blocking_gets``, so round-trips and bytes can never disagree
    about which fetches happened."""
    try:
        from ..obs.jaxcost import note_transfer
    except Exception:  # noqa: BLE001 — ledger is an enhancement
        return
    note_transfer(_tree_nbytes(values), direction="d2h")


def device_get(tree: Any) -> Any:
    """A counted blocking fetch — drop-in for ``jax.device_get`` at call
    sites that need the value NOW regardless of any batch (calibration
    probes, benches timing a single transfer)."""
    return _counted_device_get(tree, _ACTIVE.get())


class _Handle:
    """One registered tree's future host value. ``result()`` flushes the
    owning batch on first access — everything registered before that
    moment rides the same device_get."""

    __slots__ = ("_batch", "_value", "_resolved")

    def __init__(self, batch: "TransferBatch") -> None:
        self._batch = batch
        self._value: Any = None
        self._resolved = False

    def result(self) -> Any:
        if not self._resolved:
            self._batch.flush()
        return self._value


class TransferBatch:
    """All pending device→host fetches of one request.

    Stages call :meth:`register` with dispatched (still-async) device
    arrays and get a handle; ``handle.result()`` — or an explicit
    :meth:`flush` — materializes every pending tree in one blocking
    ``jax.device_get``. Registration after a flush simply opens the
    next round: a request whose stages interleave register/consume still
    pays one fetch per *wave*, never one per stage.

    Thread-safe: the request thread owns the batch via the context
    variable, but an overlap worker (the metrics route's concurrent
    forecast) may share it; ``_lock`` keeps flush atomic.
    """

    def __init__(self) -> None:
        self._pending: list[tuple[Any, _Handle]] = []
        self._lock = threading.Lock()
        #: Blocking fetches paid while this batch was active (flushes
        #: and direct counted gets alike) — the per-request number.
        self.blocking_gets = 0

    def register(self, tree: Any) -> _Handle:
        handle = _Handle(self)
        with self._lock:
            self._pending.append((tree, handle))
        return handle

    def flush(self) -> None:
        """Materialize every pending tree in ONE blocking device_get."""
        with self._lock:
            pending, self._pending = self._pending, []
        if not pending:
            return
        # The transfer-flush stage in request traces (ADR-013): on a
        # tunneled device this span IS the tunnel RTT, which is why it
        # gets first-class attribution.
        with _span("transfer.flush", trees=len(pending)):
            values = _counted_device_get([tree for tree, _h in pending], self)
        if len(pending) > 1:
            transfer_stats.record_coalesced(len(pending))
        for (_tree, handle), value in zip(pending, values):
            handle._value = value
            handle._resolved = True

    @contextlib.contextmanager
    def scope(self) -> Iterator["TransferBatch"]:
        """Install this batch for the calling context; flush leftovers on
        exit so a stage that registered but never consumed cannot leak
        an unresolved handle past the request."""
        token = _ACTIVE.set(self)
        try:
            yield self
        finally:
            _ACTIVE.reset(token)
            self.flush()


def fetch(tree: Any) -> Any:
    """THE serving-path fetch: coalesce when a request batch is active
    (register + flush-on-demand, riding one device_get with every other
    pending stage), plain counted device_get otherwise."""
    batch = _ACTIVE.get()
    if batch is None:
        return _counted_device_get(tree, None)
    return batch.register(tree).result()


def defer(tree: Any) -> Callable[[], Any]:
    """Non-blocking registration for dispatch-then-join stages: returns
    a zero-arg resolver. With a batch active the tree joins the batch;
    without one the resolver pays its own counted get when called —
    either way nothing blocks until the resolver runs."""
    batch = _ACTIVE.get()
    if batch is not None:
        handle = batch.register(tree)
        return handle.result
    return lambda: _counted_device_get(tree, None)
