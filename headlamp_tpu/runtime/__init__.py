"""Runtime services for the serving path.

Device-residency and transfer discipline live here, between the pure
analytics kernels and the HTTP host: the analytics layer says *what*
to compute, this package decides *where the arrays live* and *how many
device round-trips a request pays*.

- :mod:`transfer` — the single funnel every serving-path device→host
  fetch goes through: per-request coalescing (``TransferBatch``) plus
  the blocking-transfer counters bench.py and /healthz report.
- :mod:`device_cache` — ``DeviceFleetCache``: columnar fleets kept
  resident on device across requests, keyed by snapshot version, so
  the XLA rollup stops re-uploading host arrays on every call.
- :mod:`refresh` — ``Refresher``: keyed stale-while-revalidate cache
  (TTL + grace, single-flight) that moves expensive recomputes — the
  forecast fit above all — off the request path (ADR-015).

Everything is import-guarded: a jax-less host can import this package
(the server does) and only pays for what it calls.
"""

from .device_cache import DeviceFleetCache, fleet_cache
from .refresh import Refresher
from .transfer import (
    TransferBatch,
    active_batch,
    defer,
    device_get,
    fetch,
    transfer_stats,
)

__all__ = [
    "DeviceFleetCache",
    "Refresher",
    "TransferBatch",
    "active_batch",
    "defer",
    "device_get",
    "fetch",
    "fleet_cache",
    "transfer_stats",
]
