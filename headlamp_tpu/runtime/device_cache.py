"""Device-resident fleet arrays, keyed by snapshot version.

BENCH_r05 put ``rollup_xla_ms_1024`` at 123.8 ms against a 9.45 ms
Python pass — not compute (the fused program is flat across 256→1024
nodes) but the per-call transfer tax: every ``fleet_stats`` call
re-encoded the snapshot to host numpy and re-uploaded the columns, and
the rollup's device_get then paid the tunnel RTT on top. The upload half
of that tax is pure waste: the fleet only changes when the background
sync produces a NEW snapshot, yet the serving path re-shipped identical
bytes on every request.

:class:`DeviceFleetCache` removes it. Each provider keeps at most one
entry — the columnar :class:`~headlamp_tpu.analytics.encode.FleetArrays`
for one snapshot version, with every numpy column replaced by its
``jax.device_put`` twin. ``fleet_rollup``'s ``jnp.asarray(...)`` calls
are no-ops on committed device arrays, so the cached FleetArrays drops
into ``rollup_to_dict`` unchanged and a warm hit uploads nothing.

Invalidation contract (ADR-012): the snapshot generation IS the key. The
data context stamps a monotone ``version`` onto every ``FleetView`` it
builds; a clean background tick reuses the cached snapshot object and
therefore the version (cache hit), a changed fleet gets a new generation
(miss → re-encode + re-upload, old entry dropped). Views without a
version — CLI one-shots, tests building raw ``classify_fleet`` views —
are never cached and never served stale: they take the encode+upload
path every call, exactly the pre-cache behavior.

Failures propagate: a broken device backend must surface to
``fleet_stats``'s existing try/except so its failure memoization (and
the Python fallback) keeps working — this cache must never convert
"device broken" into "serve stale arrays".
"""

from __future__ import annotations

import dataclasses
import threading
from typing import TYPE_CHECKING

from ..obs.metrics import registry as _metrics_registry
from ..obs.trace import annotate as _annotate
from ..obs.trace import span as _span

if TYPE_CHECKING:  # pragma: no cover - typing only
    from ..analytics.encode import FleetArrays
    from ..domain.accelerator import FleetView


def _to_device(fleet: "FleetArrays") -> "FleetArrays":
    """A FleetArrays twin with every array column committed to device.
    Scalars (n_nodes/n_pods) and node_names stay host-side — the rollup
    reads them in Python."""
    import jax
    import numpy as np

    replacements = {
        field.name: jax.device_put(value)
        for field in dataclasses.fields(fleet)
        if isinstance(value := getattr(fleet, field.name), np.ndarray)
    }
    # One barrier for the whole upload: entries enter the cache fully
    # transferred, so a later hit can never block on a straggling copy.
    for arr in replacements.values():
        arr.block_until_ready()
    return dataclasses.replace(fleet, **replacements)


class DeviceFleetCache:
    """Per-provider device-resident ``FleetArrays``, one entry each,
    keyed by the view's snapshot ``version``.

    Thread-safe for the server's access pattern: the background sync
    warms it off the request path, request threads hit it concurrently.
    The lock guards only dict bookkeeping; encode + upload happen
    outside it (two threads racing the same cold version do redundant
    work once rather than serializing every warm hit behind an upload).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: dict[str, tuple[int, "FleetArrays"]] = {}
        # Dual accounting (ADR-013): the registry counters are
        # process-global (get-or-create shares them across instances)
        # and feed /metricsz; the ``_hits_n``/``_misses_n`` ints are
        # per-instance so ``hits``/``misses``/``snapshot()`` keep their
        # original fresh-instance-starts-at-zero semantics for /healthz,
        # bench deltas, and tests.
        self._hits_n = 0
        self._misses_n = 0
        self._hits = _metrics_registry.counter(
            "headlamp_tpu_fleet_cache_hits_total",
            "fleet_for calls served from device-resident arrays",
        )
        self._misses = _metrics_registry.counter(
            "headlamp_tpu_fleet_cache_misses_total",
            "fleet_for calls that paid an encode (and, versioned, an upload)",
        )
        hits, misses = self._hits, self._misses

        def _process_hit_ratio() -> float:
            total = hits.value + misses.value
            return hits.value / total if total else 0.0

        # Closes over the shared counters, not self: the ratio stays
        # process-wide even when tests churn through instances (each
        # __init__ re-registers, but every closure computes the same
        # global value).
        _metrics_registry.gauge_fn(
            "headlamp_tpu_fleet_cache_hit_ratio",
            "Device fleet cache hit ratio since process start",
            _process_hit_ratio,
        )

    @property
    def hits(self) -> int:
        return self._hits_n

    @property
    def misses(self) -> int:
        return self._misses_n

    def fleet_for(self, view: "FleetView") -> "FleetArrays":
        """The columnar fleet for ``view`` — device-resident from cache
        when the version matches, freshly encoded (and cached when the
        view carries a version) otherwise. Annotates the enclosing
        trace span (the rollup's) with the hit/miss outcome — whether a
        slow rollup paid an upload is the first thing a trace reader
        needs to know."""
        from ..analytics.encode import encode_fleet

        version = getattr(view, "version", None)
        provider = view.provider.name
        if version is not None:
            with self._lock:
                entry = self._entries.get(provider)
                if entry is not None and entry[0] == version:
                    self._hits_n += 1
                    self._hits.inc()
                    _annotate(fleet_cache="hit")
                    return entry[1]
            self._misses_n += 1
            self._misses.inc()
            _annotate(fleet_cache="miss")
            with _span("device_cache.upload", nodes=len(view.nodes)):
                fleet = _to_device(encode_fleet(view.nodes, view.pods))
            with self._lock:
                self._entries[provider] = (version, fleet)
            return fleet
        # Unversioned view: pre-cache behavior, host arrays every call.
        self._misses_n += 1
        self._misses.inc()
        _annotate(fleet_cache="unversioned")
        return encode_fleet(view.nodes, view.pods)

    def seed(
        self,
        provider: str,
        version: int,
        fleet: "FleetArrays",
        *,
        to_device: bool = True,
    ) -> None:
        """Install pre-built columns for ``(provider, version)`` without
        running ``encode_fleet`` — the ADR-029 shared-memory fast path:
        a worker that attached a published segment already HOLDS the
        contiguous columns, so the first render of that generation
        must not pay the per-node encode loop again. ``to_device``
        uploads eagerly (same contract as ``warm``); on a jax-less
        host the host arrays are seeded as-is — ``fleet_for`` already
        serves host arrays on its unversioned path, so downstream
        handles both. Same invalidation contract as every other entry:
        the generation is the key, a newer seed replaces the entry."""
        if to_device:
            try:
                fleet = _to_device(fleet)
            except Exception:  # noqa: BLE001 — jax-less host: host columns still serve
                pass
        with self._lock:
            self._entries[provider] = (int(version), fleet)

    def warm(self, view: "FleetView") -> bool:
        """Background-sync hook: encode + upload ``view`` now so the
        next request hits warm. Swallows nothing — but the caller (the
        sync loop) treats any exception as non-fatal, mirroring how
        calibration failures are handled there. Returns True when an
        upload happened, False when the entry was already current or
        the view is unversioned."""
        version = getattr(view, "version", None)
        if version is None:
            return False
        from ..analytics.encode import encode_fleet

        with self._lock:
            entry = self._entries.get(view.provider.name)
            if entry is not None and entry[0] == version:
                return False
        fleet = _to_device(encode_fleet(view.nodes, view.pods))
        with self._lock:
            self._entries[view.provider.name] = (version, fleet)
        return True

    def invalidate(self) -> None:
        """Drop every entry (operator lever, rides /refresh's cache
        epoch bump; also frees device memory on demand)."""
        with self._lock:
            self._entries.clear()

    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def counters(self) -> dict[str, int]:
        """Monotone counters only, lock-free (plain int reads) — the
        flight recorder's per-request delta view. No hit_rate (a gauge
        would make deltas noisy) and no entries copy (cost)."""
        return {"hits": self.hits, "misses": self.misses}

    def snapshot(self) -> dict[str, object]:
        """Observability block for /healthz and bench."""
        with self._lock:
            entries = {name: version for name, (version, _f) in self._entries.items()}
        return {
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": round(self.hit_rate(), 4),
            "entries": entries,
        }


#: Process-wide cache instance — one device, one resident fleet set.
fleet_cache = DeviceFleetCache()


class RollupResultCache:
    """Host-side rollup dicts the fused rollup+forecast program already
    computed (ADR-020), keyed ``(provider, snapshot version)`` with one
    entry per provider — the same invalidation contract as
    :class:`DeviceFleetCache` (the generation IS the key, so a stale
    entry can never serve a newer fleet).

    The fused request path computes the rollup and the forecast in ONE
    donated device program and fetches both in one device_get; parking
    the finalized rollup dict here lets the overview's ``fleet_stats``
    call for the same snapshot serve it with ZERO device work instead
    of re-dispatching the standalone rollup. Entries are stored
    finalized (post ``rollup_host_view``) and handed out as copies so
    the per-request ``generation_counts`` override can't mutate the
    cached dict."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: dict[str, tuple[int, dict]] = {}
        self.hits = 0
        self.lookups = 0

    def store(self, provider: str, version: int, stats: dict) -> None:
        with self._lock:
            self._entries[provider] = (version, dict(stats))

    def get(self, provider: str, version: "int | None") -> "dict | None":
        if version is None:
            return None
        with self._lock:
            self.lookups += 1
            entry = self._entries.get(provider)
            if entry is None or entry[0] != version:
                return None
            self.hits += 1
            return dict(entry[1])

    def invalidate(self) -> None:
        with self._lock:
            self._entries.clear()

    def counters(self) -> dict[str, int]:
        return {"hits": self.hits, "lookups": self.lookups}


#: Process-wide fused-rollup result cache (ADR-020).
rollup_results = RollupResultCache()


class WarmCarryCache:
    """Process-scoped warm-start forecast carries (ADR-020), the third
    cache tier after the fleet columns and the fused rollup results.
    ADR-015's warm starts were app-scoped: a carry lived in one
    ``DashboardApp``'s dict, so any host that rebuilds the app — a
    fresh process serving its first request, the bench's fresh-app
    discipline, a CLI one-shot — paid the full cold fit (~6x the warm
    step budget of device compute) even though the process had already
    learned perfectly good parameters for that exact chip set.

    Unlike the other two tiers this one stages on HOST: ``store()``
    copies every ``jax.Array`` leaf to numpy before keeping it. Two
    reasons. First, lifetime: this cache lives at module scope, and a
    module global releasing device buffers during interpreter teardown
    races XLA's own static destructors — the exit segfaults after the
    last test has already passed. Host arrays have no destructor
    ordering against the backend. Second, donation: the warm fit
    program donates its params/opt_state operands, so a device-resident
    carry would be dead after one dispatch; a host carry mints fresh
    device buffers at each ``device_put``, making the donated copy a
    throwaway. The staging ``device_get`` also doubles as a completion
    fence — a stored carry is never an in-flight computation. Cost:
    one ~2 MB device→host copy per refit, off the request path.

    ``take()`` still pops: a carry is refined in place by the fit that
    consumes it, so leaving it visible would let a concurrent taker
    race the same lineage and double-fit. The loser of the pop
    cold-fits — correct, merely slower. The caller stores the NEW
    carry when the fit returns.

    Keys are whatever the caller derives from chip identity (the app's
    ``_metrics_key``); entries evict LRU beyond ``max_keys`` — a carry
    is ~2 MB of params + adam moments, and a dashboard serves a
    handful of fleets, not hundreds. Quality is guarded downstream,
    not here: the warm path's MSE demotion check (ADR-015) cold-refits
    whenever a carried fit underperforms, so a stale carry can degrade
    one fit's latency, never its served accuracy."""

    def __init__(self, *, max_keys: int = 8) -> None:
        self._lock = threading.Lock()
        self._entries: dict[object, object] = {}
        self.max_keys = max_keys
        self.hits = 0
        self.lookups = 0
        self.evictions = 0

    def take(self, key: object) -> object | None:
        """Remove and return the carry for ``key`` (None on miss). Pop,
        not peek: see class docstring — one fit per lineage at a time."""
        with self._lock:
            self.lookups += 1
            state = self._entries.pop(key, None)
            if state is not None:
                self.hits += 1
            return state

    @staticmethod
    def _host_staged(state: object) -> object:
        """Copy every jax.Array leaf to numpy; non-array leaves (cfg,
        host floats, generation counters) pass through as pytree
        leaves untouched."""
        import jax
        import numpy as np

        return jax.tree_util.tree_map(
            lambda leaf: np.asarray(jax.device_get(leaf))
            if isinstance(leaf, jax.Array)
            else leaf,
            state,
        )

    def store(self, key: object, state: object) -> None:
        try:
            state = self._host_staged(state)
        except Exception:
            # No jax / unmappable state: a device-resident carry still
            # works, it just loses the teardown-safety guarantee.
            pass
        with self._lock:
            # Re-insert at the end: dict order is the LRU eviction order.
            self._entries.pop(key, None)
            self._entries[key] = state
            while len(self._entries) > self.max_keys:
                oldest = next(iter(self._entries))
                del self._entries[oldest]
                self.evictions += 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def invalidate(self) -> None:
        with self._lock:
            self._entries.clear()

    def counters(self) -> dict[str, int]:
        return {
            "hits": self.hits,
            "lookups": self.lookups,
            "evictions": self.evictions,
        }


#: Process-wide warm-carry store (ADR-020): fitted params + optimizer
#: state survive app reconstruction, so only a chip-set never seen by
#: THIS PROCESS pays a cold fit.
warm_carries = WarmCarryCache()
