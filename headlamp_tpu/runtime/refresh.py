"""Stale-while-revalidate keyed refresher (ADR-015).

The serving-path answer to the cold-fit cliff: BENCH_r06 put the cold
forecast fit at ~2.4 s, and before this module that cost landed on
whichever USER REQUEST happened to hit the TTL lapse — while holding
the cache lock, so every concurrent metrics view stalled behind it.

:class:`Refresher` makes expiry a background event instead of a
request-path one:

- **fresh** (``age ≤ ttl_s``): serve from cache, touch nothing.
- **stale** (``ttl_s < age ≤ grace_s``): serve the stale value
  IMMEDIATELY and kick exactly one background recompute (single-flight
  per key+epoch); the next request after it lands sees fresh data.
- **cold / past grace / epoch bumped**: the only case that blocks —
  and concurrent requests for the same key join the in-flight compute
  rather than duplicating it.

Clock discipline (ADR-013): every age comparison runs on the injected
``monotonic`` — tests drive expiry by advancing a list cell, never by
sleeping. Wall clock never enters the math.

Failure policy: a FOREGROUND compute error propagates to every joined
waiter (they asked for a value and there is none). A BACKGROUND refit
error is absorbed — the stale value keeps serving until grace runs
out, which degrades exactly like the pre-refresher cache would have,
except the error is counted (``refit_errors`` in :meth:`snapshot`)
instead of silent.

Stdlib-only: the server imports this unconditionally; the values being
refreshed (fleet metrics, forecast views) are opaque here.
"""

from __future__ import annotations

import contextvars
import threading
import time
from typing import Any, Callable, Hashable

from ..obs.metrics import registry as _metrics_registry
from ..obs.trace import span as _span

# Registry instruments (ADR-013 get-or-create; the ``refresher`` label
# separates the metrics cache from the forecast cache). Per-instance
# ints in Refresher stay the /healthz + test view; both are written on
# the same transitions so the surfaces cannot disagree.
_SERVED_FRESH = _metrics_registry.counter(
    "headlamp_tpu_refresh_served_fresh_total",
    "Cache reads answered by a within-TTL value (no work scheduled).",
    labels=("refresher",),
)
_SERVED_STALE = _metrics_registry.counter(
    "headlamp_tpu_refresh_served_stale_total",
    "Cache reads answered by a stale-but-in-grace value while a "
    "background refresh ran — request-path stalls this design removed.",
    labels=("refresher",),
)
_REFITS = _metrics_registry.counter(
    "headlamp_tpu_refresh_refits_total",
    "Recomputes executed (foreground cold fills + background refreshes).",
    labels=("refresher",),
)
_DEMOTIONS = _metrics_registry.counter(
    "headlamp_tpu_refresh_demotions_to_cold_total",
    "Warm-start fits demoted to cold refits by the ADR-015 MSE check "
    "(reported by the compute fn via note_demotion).",
    labels=("refresher",),
)
_FIT_HIST = _metrics_registry.histogram(
    "headlamp_tpu_refresh_fit_duration_seconds",
    "Wall duration of refresher recomputes (the cost the grace window "
    "hides from the request path).",
    labels=("refresher",),
)


class _Flight:
    """One in-flight compute for a (key, epoch): late arrivals wait on
    ``done`` instead of recomputing."""

    __slots__ = ("done", "value", "error")

    def __init__(self) -> None:
        self.done = threading.Event()
        self.value: Any = None
        self.error: BaseException | None = None


class _Entry:
    __slots__ = ("value", "fetched_mono", "epoch")

    def __init__(self, value: Any, fetched_mono: float, epoch: int) -> None:
        self.value = value
        self.fetched_mono = fetched_mono
        self.epoch = epoch


class Refresher:
    """Keyed single-flight cache with a TTL (fresh) + grace (stale-
    servable) window. ``compute`` callables ALWAYS run outside the map
    lock — the whole point is that a multi-second fit never blocks
    readers of other keys (or, within grace, of the same key)."""

    def __init__(
        self,
        name: str,
        *,
        ttl_s: float,
        grace_s: float,
        monotonic: Callable[[], float] | None = None,
        max_entries: int = 8,
    ) -> None:
        if grace_s < ttl_s:
            raise ValueError("grace_s must be >= ttl_s (grace extends the TTL)")
        self.name = name
        self.ttl_s = ttl_s
        self.grace_s = grace_s
        self.max_entries = max_entries
        self._monotonic = monotonic or time.monotonic
        self._lock = threading.Lock()
        self._entries: dict[Hashable, _Entry] = {}
        self._flights: dict[tuple[Hashable, int], _Flight] = {}
        # /healthz + test view (registry counters are the fleet view).
        self.served_fresh = 0
        self.served_stale = 0
        self.refits = 0
        self.refit_errors = 0
        self.demotions_to_cold = 0
        # Capture seam (ADR-018): called with (key, value) after every
        # successful store, outside the map lock. Runs on the refit
        # thread for background refreshes and on the requesting thread
        # only for cold foreground fills, so a hook costs the
        # steady-state request path nothing. Hook failures are absorbed
        # — history capture must never poison the cache it observes.
        self.on_store: Callable[[Hashable, Any], None] | None = None

    # -- read paths ------------------------------------------------------

    def get(
        self, key: Hashable, compute: Callable[[], Any], *, epoch: int = 0
    ) -> Any:
        """Value for ``key``, running/joining ``compute`` as needed.
        Blocks only when no same-epoch value within grace exists."""
        now = self._monotonic()
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and entry.epoch == epoch:
                age = now - entry.fetched_mono
                if age <= self.ttl_s:
                    self.served_fresh += 1
                    _SERVED_FRESH.inc(refresher=self.name)
                    return entry.value
                if age <= self.grace_s:
                    # Serve stale NOW; exactly one background refresh.
                    self.served_stale += 1
                    _SERVED_STALE.inc(refresher=self.name)
                    self._spawn_refit_locked(key, epoch, compute)
                    return entry.value
            # Cold / past grace / epoch bumped: block (or join a flight).
            fkey = (key, epoch)
            flight = self._flights.get(fkey)
            if flight is None:
                flight = _Flight()
                self._flights[fkey] = flight
                leader = True
            else:
                leader = False
        if leader:
            return self._foreground_fill(key, epoch, compute, flight)
        flight.done.wait()
        if flight.error is not None:
            raise flight.error
        return flight.value

    def get_nowait(
        self, key: Hashable, compute: Callable[[], Any], *, epoch: int = 0
    ) -> Any | None:
        """Non-blocking get: fresh and stale-within-grace values return
        immediately (stale kicks exactly one background refresh, same
        as :meth:`get`); a cold / past-grace / epoch-bumped key kicks
        the single-flight compute in the BACKGROUND and returns None
        instead of blocking. For surfaces that must render on every
        request (e.g. /sloz's budget forecast) where "not computed yet"
        is a renderable state and a foreground model fit is not."""
        now = self._monotonic()
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and entry.epoch == epoch:
                age = now - entry.fetched_mono
                if age <= self.ttl_s:
                    self.served_fresh += 1
                    _SERVED_FRESH.inc(refresher=self.name)
                    return entry.value
                if age <= self.grace_s:
                    self.served_stale += 1
                    _SERVED_STALE.inc(refresher=self.name)
                    self._spawn_refit_locked(key, epoch, compute)
                    return entry.value
            self._spawn_refit_locked(key, epoch, compute)
            return None

    def peek(
        self, key: Hashable, *, epoch: int = 0, max_age_s: float | None = None
    ) -> Any | None:
        """Non-blocking read: the cached value if it matches ``epoch``
        and is younger than ``max_age_s`` (default: the grace window),
        else None. Never computes."""
        limit = self.grace_s if max_age_s is None else max_age_s
        now = self._monotonic()
        with self._lock:
            entry = self._entries.get(key)
            if entry is None or entry.epoch != epoch:
                return None
            if now - entry.fetched_mono > limit:
                return None
            return entry.value

    # -- compute paths ---------------------------------------------------

    def _spawn_refit_locked(
        self, key: Hashable, epoch: int, compute: Callable[[], Any]
    ) -> None:
        """Start the single-flight background compute for (key, epoch)
        unless one is already running. Caller holds ``self._lock``.
        Copies the caller's contextvars into the worker (same pattern
        as the transport fan-out, transport/pool.py): the background
        refit's ``refresh.fit`` span then attaches to the REQUESTING
        trace instead of orphaning, and exemplar capture sees the right
        trace id."""
        fkey = (key, epoch)
        if fkey in self._flights:
            return
        flight = _Flight()
        self._flights[fkey] = flight
        ctx = contextvars.copy_context()
        threading.Thread(
            target=ctx.run,
            args=(self._background_refit, key, epoch, compute, flight),
            name=f"refresh-{self.name}",
            daemon=True,
        ).start()

    def _run_compute(self, compute: Callable[[], Any]) -> Any:
        """The timed, traced recompute — shared by foreground and
        background so the histogram sees every fit."""
        t0 = time.perf_counter()
        try:
            with _span("refresh.fit", refresher=self.name):
                return compute()
        finally:
            _FIT_HIST.observe(time.perf_counter() - t0, refresher=self.name)

    def _store(self, key: Hashable, value: Any, epoch: int) -> None:
        with self._lock:
            self._entries[key] = _Entry(value, self._monotonic(), epoch)
            self.refits += 1
            while len(self._entries) > self.max_entries:
                oldest = min(
                    self._entries, key=lambda k: self._entries[k].fetched_mono
                )
                del self._entries[oldest]
        _REFITS.inc(refresher=self.name)
        hook = self.on_store
        if hook is not None:
            try:
                hook(key, value)
            except Exception:  # noqa: BLE001 — capture never breaks caching
                pass

    def _foreground_fill(
        self,
        key: Hashable,
        epoch: int,
        compute: Callable[[], Any],
        flight: _Flight,
    ) -> Any:
        try:
            value = self._run_compute(compute)
        except BaseException as exc:
            with self._lock:
                self.refit_errors += 1
                self._flights.pop((key, epoch), None)
            flight.error = exc
            flight.done.set()
            raise
        self._store(key, value, epoch)
        with self._lock:
            self._flights.pop((key, epoch), None)
        flight.value = value
        flight.done.set()
        return value

    def _background_refit(
        self,
        key: Hashable,
        epoch: int,
        compute: Callable[[], Any],
        flight: _Flight,
    ) -> None:
        try:
            value = self._run_compute(compute)
        except Exception:
            # Absorbed by design: the stale value keeps serving until
            # grace runs out — same degradation as the pre-refresher
            # cache, but counted instead of silent.
            with self._lock:
                self.refit_errors += 1
                self._flights.pop((key, epoch), None)
            flight.done.set()
            return
        except BaseException:
            # KeyboardInterrupt/SystemExit: unwind the flight so
            # waiters don't hang, but never spend refit_errors on an
            # interrupt — and let it take the worker down.
            with self._lock:
                self._flights.pop((key, epoch), None)
            flight.done.set()
            raise
        self._store(key, value, epoch)
        with self._lock:
            self._flights.pop((key, epoch), None)
        flight.value = value
        flight.done.set()

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Block until no compute is in flight (or ``timeout_s`` runs
        out; returns False then). For tests and benchmarks that must
        not race a background refit across an assertion or process
        exit — the serving path never calls this. Waits on REAL time:
        the injected monotonic only governs ages, and tests freeze it."""
        deadline = time.monotonic() + timeout_s
        while True:
            with self._lock:
                flights = list(self._flights.values())
            if not flights:
                return True
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            flights[0].done.wait(min(remaining, 0.25))

    # -- observability ---------------------------------------------------

    def note_demotion(self) -> None:
        """Record an ADR-015 warm→cold demotion (the compute fn knows;
        the refresher owns the counter surfaces)."""
        with self._lock:
            self.demotions_to_cold += 1
        _DEMOTIONS.inc(refresher=self.name)

    def counters(self) -> dict[str, int]:
        """Monotone counters only, lock-free — the flight recorder's
        per-request delta view (snapshot minus the ``entries`` gauge,
        and without taking the map lock)."""
        return {
            "served_fresh": self.served_fresh,
            "served_stale": self.served_stale,
            "refits": self.refits,
            "refit_errors": self.refit_errors,
            "demotions_to_cold": self.demotions_to_cold,
        }

    def snapshot(self) -> dict[str, int]:
        """Plain-int view for /healthz (mirrors the registry counters)."""
        with self._lock:
            return {
                "served_fresh": self.served_fresh,
                "served_stale": self.served_stale,
                "refits": self.refits,
                "refit_errors": self.refit_errors,
                "demotions_to_cold": self.demotions_to_cold,
                "entries": len(self._entries),
            }
