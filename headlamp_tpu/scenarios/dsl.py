"""Scenario DSL (ADR-030): declarative phased incident drills.

A :class:`ScenarioSpec` is a named, ordered tuple of :class:`Phase`
objects — **inject** (break something), **hold** (let the observability
stack react), **recover** (un-break it and watch it stand down). Each
phase has a scripted duration and two action lists: ``enter`` runs once
at the phase boundary, ``tick`` runs every ``tick_s`` of scripted time
inside the phase. Actions are plain callables over the runner's
:class:`~.runner.ScenarioContext` — the DSL owns *when*, the injectors
(inject.py) own *what*, the runner owns *driving*.

Everything here is scripted on the injected monotonic clock (ADR-013,
enforced by WCK001 over this package): a "5 minute" hold advances a
fake clock 5 minutes in microseconds of real time, which is what makes
two runs of one scenario byte-identical (ADR-018) and the whole matrix
cheap enough to regression-gate.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Tuple

#: An action over the runner's ScenarioContext. Actions mutate faults,
#: drive traffic, or feed the SLO engine — never sleep, never read the
#: real clock.
Action = Callable[[Any], None]

#: The three legal phase kinds, in the order a drill runs them.
PHASE_KINDS = ("inject", "hold", "recover")


class ScenarioError(Exception):
    """A malformed spec (bad phase kind, non-positive duration)."""


class ScenarioAssertionError(AssertionError):
    """A response assertion tripped: the observability stack did not
    react to the drill the way the scenario demands. Carries the
    scenario and check names so a matrix failure reads as WHICH drill
    and WHICH promise."""

    def __init__(self, scenario: str, check: str, message: str) -> None:
        super().__init__(f"[{scenario}] {check}: {message}")
        self.scenario = scenario
        self.check = check


@dataclass(frozen=True)
class Phase:
    """One scripted phase of a drill."""

    kind: str
    duration_s: float
    enter: Tuple[Action, ...] = ()
    tick: Tuple[Action, ...] = ()

    def __post_init__(self) -> None:
        if self.kind not in PHASE_KINDS:
            raise ScenarioError(
                f"phase kind {self.kind!r} not one of {PHASE_KINDS}"
            )
        if self.duration_s <= 0:
            raise ScenarioError(
                f"phase {self.kind!r} duration must be > 0, got {self.duration_s}"
            )


@dataclass(frozen=True)
class ScenarioSpec:
    """One named drill: phases plus the response checks that gate it.

    ``checks`` are callables over the completed
    :class:`~.runner.ScenarioReport`; each raises
    :class:`ScenarioAssertionError` when its promise is broken.
    ``read_tier`` asks the runner to build a leader+replica pair
    (ADR-025) instead of a single app — the leader-kill drill needs a
    successor to fail over to."""

    name: str
    description: str
    phases: Tuple[Phase, ...]
    tick_s: float = 30.0
    checks: Tuple[Callable[[Any], None], ...] = ()
    read_tier: bool = False
    extra: dict[str, Any] = field(default_factory=dict)

    def __post_init__(self) -> None:
        if not self.phases:
            raise ScenarioError(f"scenario {self.name!r} has no phases")
        if self.tick_s <= 0:
            raise ScenarioError(
                f"scenario {self.name!r} tick_s must be > 0, got {self.tick_s}"
            )
        order = [p.kind for p in self.phases]
        # Phases must not regress (an inject after a recover is a new
        # scenario, not a phase): enforce monotone kind order.
        ranks = [PHASE_KINDS.index(k) for k in order]
        if ranks != sorted(ranks):
            raise ScenarioError(
                f"scenario {self.name!r} phases out of order: {order}"
            )

    def ticks_in(self, phase: Phase) -> int:
        """Whole ticks the runner executes inside ``phase``."""
        return max(int(phase.duration_s // self.tick_s), 1)


__all__ = [
    "Action",
    "PHASE_KINDS",
    "Phase",
    "ScenarioAssertionError",
    "ScenarioError",
    "ScenarioSpec",
]
