"""The incident library (ADR-030): six named drills.

Each spec scripts a fault the stack has a designed response to, and its
checks assert that response end-to-end. Durations are scripted seconds
(SLOT_S = 60 s windows; ticks default 30 s) — the whole matrix runs in
well under a second of real time.

Timing note for the burn drills: a page needs BOTH fast windows (5 m,
1 h) above 14.4×, and clears when the 5 m window drains — ~300 s of
scripted time after the last bad observation — so recover phases run
≥ 360 s and the recovery budget is 8 windows.
"""

from __future__ import annotations

from . import assertions as a
from . import inject as inj
from .dsl import Phase, ScenarioSpec


def _preemption_wave() -> ScenarioSpec:
    return ScenarioSpec(
        name="preemption_wave",
        description=(
            "A wave of TPU node preemptions churns the fleet while "
            "dashboard renders slow past the SLO threshold; the stack "
            "must page fast, shed debug, degrade interactive, and stand "
            "down after the fleet restores."
        ),
        phases=(
            Phase(
                "inject",
                90.0,
                tick=(
                    inj.preemption_wave(per_tick=2),
                    inj.slow_paints("/tpu", 1.2, 20),
                ),
            ),
            Phase("hold", 120.0, tick=(inj.slow_paints("/tpu", 1.2, 20),)),
            Phase(
                "recover",
                390.0,
                enter=(inj.restore_fleet(),),
                tick=(inj.good_paints("/tpu", 30),),
            ),
        ),
        checks=(
            a.assert_pages_within(4.0),
            a.assert_debug_sheds_first(),
            a.assert_zero_5xx(),
            a.assert_recovery_unpages(8.0),
        ),
    )


def _prom_flapping() -> ScenarioSpec:
    return ScenarioSpec(
        name="prom_flapping",
        description=(
            "The Prometheus proxy flaps — alternating ticks of 503s and "
            "slow scrapes — and scrape_paint burns; ops surfaces stay "
            "untouchable throughout and the page clears once the scrape "
            "path heals."
        ),
        phases=(
            Phase("inject", 60.0, tick=(inj.prometheus_flap(bad_per_tick=12),)),
            Phase("hold", 120.0, tick=(inj.prometheus_flap(bad_per_tick=12),)),
            Phase(
                "recover",
                390.0,
                enter=(inj.transport_errors(False, match=("prometheus",)),),
                tick=(inj.good_paints("/tpu/metrics", 20, latency_s=0.3),),
            ),
        ),
        checks=(
            a.assert_pages_within(4.0),
            a.assert_debug_sheds_first(),
            a.assert_zero_5xx(),
            a.assert_recovery_unpages(8.0),
        ),
    )


def _hub_restart_herd() -> ScenarioSpec:
    return ScenarioSpec(
        name="hub_restart_herd",
        description=(
            "The push hub restarts (worker bounce) and six SSE clients "
            "stampede back with pre-restart Last-Event-IDs; every resume "
            "must be answered with an honest full-paint fallback, never "
            "fabricated deltas."
        ),
        tick_s=15.0,
        phases=(
            Phase("inject", 30.0, tick=(inj.publish_frames(8),)),
            Phase(
                "hold",
                30.0,
                enter=(inj.hub_restart(clients=6),),
                tick=(inj.publish_frames(4),),
            ),
            Phase("recover", 30.0, tick=(inj.publish_frames(4),)),
        ),
        checks=(
            a.assert_hub_honest(min_clients=6),
            a.assert_zero_5xx(),
            a.assert_never_pages(),
        ),
    )


def _slow_loris_sse() -> ScenarioSpec:
    return ScenarioSpec(
        name="slow_loris_sse",
        description=(
            "Two SSE consumers stop reading while fleet churn keeps "
            "publishing; their bounded outboxes must fill and the hub "
            "must evict each with exactly one honest bye frame."
        ),
        tick_s=15.0,
        phases=(
            Phase(
                "inject",
                15.0,
                enter=(inj.slow_loris(subscribers=2),),
                tick=(inj.publish_frames(24),),
            ),
            Phase("hold", 30.0, tick=(inj.publish_frames(24),)),
            Phase("recover", 15.0, tick=(inj.publish_frames(4),)),
        ),
        checks=(
            a.assert_slow_consumers_evicted(2),
            a.assert_zero_5xx(),
            a.assert_never_pages(),
        ),
    )


def _clock_skew_scrape() -> ScenarioSpec:
    return ScenarioSpec(
        name="clock_skew_scrape",
        description=(
            "The wall clock steps one hour forward mid-scrape (NTP "
            "correction) while healthy traffic continues; every TTL, "
            "burn window, and staleness probe rides the monotonic clock "
            "(ADR-013), so nothing may page, degrade, or 5xx."
        ),
        phases=(
            Phase("inject", 60.0, enter=(inj.clock_skew(3600.0),)),
            Phase("hold", 120.0),
            Phase("recover", 60.0),
        ),
        checks=(
            a.assert_never_pages(("scrape_paint", "dashboard_render")),
            a.assert_no_stale_paints(),
            a.assert_zero_5xx(),
        ),
    )


def _leader_kill_mid_churn() -> ScenarioSpec:
    return ScenarioSpec(
        name="leader_kill_mid_churn",
        description=(
            "The read-tier leader dies mid preemption churn; the replica "
            "must degrade its paints honestly while the feed is silent, "
            "the standby must take over with a higher fencing term, and "
            "the zombie leader's generation-band writes must be rejected."
        ),
        read_tier=True,
        phases=(
            Phase(
                "inject",
                90.0,
                tick=(inj.preemption_wave(per_tick=1), inj.leader_publish()),
            ),
            Phase("hold", 120.0, enter=(inj.kill_leader(),)),
            Phase(
                "recover",
                120.0,
                tick=(
                    inj.standby_takeover(),
                    inj.leader_publish(),
                    inj.stale_publish(1),
                ),
            ),
        ),
        checks=(
            a.assert_failover(min_rejected=3),
            a.assert_stale_paints_during_outage(),
            a.assert_zero_5xx(),
        ),
    )


_BUILDERS = {
    "preemption_wave": _preemption_wave,
    "prom_flapping": _prom_flapping,
    "hub_restart_herd": _hub_restart_herd,
    "slow_loris_sse": _slow_loris_sse,
    "clock_skew_scrape": _clock_skew_scrape,
    "leader_kill_mid_churn": _leader_kill_mid_churn,
}

#: Stable drill order (bench rounds and the test matrix iterate this).
SCENARIO_NAMES: tuple[str, ...] = tuple(_BUILDERS)


def get_scenario(name: str) -> ScenarioSpec:
    """Build a fresh spec by name (fresh = no shared closure state
    between runs; injectors keep per-run state on the context)."""
    try:
        return _BUILDERS[name]()
    except KeyError:
        raise KeyError(
            f"unknown scenario {name!r}; known: {', '.join(SCENARIO_NAMES)}"
        ) from None


def all_scenarios() -> list[ScenarioSpec]:
    return [get_scenario(name) for name in SCENARIO_NAMES]


__all__ = ["SCENARIO_NAMES", "all_scenarios", "get_scenario"]
