"""Response assertions (ADR-030): what the observability stack must DO.

Each factory returns a check over a completed
:class:`~.runner.ScenarioReport`; a broken promise raises
:class:`~.dsl.ScenarioAssertionError` carrying the scenario and check
names. Checks assert the stack's RESPONSE to the fault — paging within
budget, shedding the right class first, honest resume, zero 5xx,
standing down after recovery — not implementation internals, so they
keep passing across refactors and keep FIRING against the broken-policy
doubles in tests/test_scenarios.py (the fires/clean discipline,
ADR-015).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable

from .dsl import ScenarioAssertionError

Check = Callable[[Any], None]


def _fail(report: Any, check: str, message: str) -> None:
    raise ScenarioAssertionError(report.name, check, message)


def assert_pages_within(max_windows: float) -> Check:
    """The burn must PAGE within ``max_windows`` SLOT_S windows of the
    first injection — detection latency is the first SLO of an
    observability stack."""

    def check(report: Any) -> None:
        windows = report.metrics.get("windows_to_page")
        if windows is None:
            _fail(
                report,
                "pages_within",
                f"no paging transition observed within the drill "
                f"(expected within {max_windows} windows of first injection)",
            )
        if windows > max_windows:
            _fail(
                report,
                "pages_within",
                f"paged after {windows} windows, budget {max_windows}",
            )

    return check


def assert_debug_sheds_first() -> Check:
    """Under the page, DEBUG traffic sheds (fast 503s) while
    INTERACTIVE traffic is never shed — it degrades to stale paints
    instead (ADR-017's priority judgement, end to end)."""

    def check(report: Any) -> None:
        counts = report.counters
        if not counts.get("debug_shed"):
            _fail(
                report,
                "debug_sheds_first",
                f"no debug request was shed "
                f"(debug_total={counts.get('debug_total', 0)})",
            )
        if not counts.get("interactive_degraded"):
            _fail(
                report,
                "debug_sheds_first",
                "no interactive render degraded to a stale paint while "
                "the SLO paged",
            )

    return check


def assert_zero_5xx() -> Check:
    """No request may 5xx end-to-end during the drill. Gateway shed
    503s are excluded by construction — shedding debug traffic is the
    intended response, not a failure."""

    def check(report: Any) -> None:
        if not report.metrics.get("zero_5xx", False):
            _fail(
                report,
                "zero_5xx",
                f"{report.counters.get('non_shed_5xx', 0)} non-shed 5xx "
                "responses served during the drill",
            )

    return check


def assert_recovery_unpages(max_windows: float = 6.0) -> Check:
    """After the recover phase starts, paging must clear (a gateway
    ``restore`` event) within ``max_windows`` windows, and every SLO
    must end the drill out of the page state — an alert that never
    stands down is as broken as one that never fires."""

    def check(report: Any) -> None:
        windows = report.metrics.get("recovery_windows")
        if windows is None:
            _fail(
                report,
                "recovery_unpages",
                "paging never cleared after the recover phase began",
            )
        if windows > max_windows:
            _fail(
                report,
                "recovery_unpages",
                f"paging cleared {windows} windows after recovery, "
                f"budget {max_windows}",
            )
        final = report.metrics.get("final_states", {})
        still = sorted(n for n, s in final.items() if s == "page")
        if still:
            _fail(
                report,
                "recovery_unpages",
                f"SLOs still paging at drill end: {still}",
            )

    return check


def assert_never_pages(slos: Iterable[str] = ()) -> Check:
    """The drill must NOT page — the fault is one the stack is supposed
    to absorb (a wall-clock step under ADR-013 clocks). With ``slos``,
    only those objectives are held to it; without, all of them."""

    names = tuple(slos)

    def check(report: Any) -> None:
        for mono, states in report.states_history:
            for name, state in states.items():
                if names and name not in names:
                    continue
                if state == "page":
                    _fail(
                        report,
                        "never_pages",
                        f"SLO {name!r} paged at mono={mono} — the stack "
                        "flinched at a fault it must absorb",
                    )

    return check


def assert_no_stale_paints() -> Check:
    """No interactive render may degrade during the drill — the
    wall-skew drill's core promise: staleness and TTL math ride the
    monotonic clock, so a wall step must not fake a stale feed."""

    def check(report: Any) -> None:
        degraded = report.counters.get("interactive_degraded", 0)
        if degraded:
            _fail(
                report,
                "no_stale_paints",
                f"{degraded} interactive renders degraded to stale "
                "paints with no real staleness present",
            )

    return check


def assert_hub_honest(min_clients: int = 1) -> Check:
    """Every post-restart resume must be answered honestly: the fresh
    hub retains no backlog, so each herd client gets full-paint
    fallbacks (reason ``resync``) — never replayed deltas the hub
    cannot actually vouch for (ADR-021)."""

    def check(report: Any) -> None:
        herds = report.extra.get("herd_events")
        if not herds or len(herds) < min_clients:
            _fail(
                report,
                "hub_honest",
                f"expected ≥{min_clients} reconnecting clients, "
                f"saw {len(herds or [])}",
            )
        fallbacks = report.extra.get("resume_fallbacks", 0)
        if fallbacks < min_clients:
            _fail(
                report,
                "hub_honest",
                f"only {fallbacks} resume fallbacks for "
                f"{len(herds)} herd clients — the hub replayed history "
                "it does not retain",
            )
        for i, events in enumerate(herds):
            if not events:
                continue
            first = events[0]
            if first["kind"] != "paint" or first["data"].get("reason") != "resync":
                _fail(
                    report,
                    "hub_honest",
                    f"herd client {i}'s first frame was "
                    f"{first['kind']!r}/{first['data'].get('reason')!r}, "
                    "not an honest resync paint",
                )

    return check


def assert_slow_consumers_evicted(count: int) -> Check:
    """Each slow-loris subscriber must be evicted as a slow consumer
    with exactly one honest ``bye`` frame queued — bounded outboxes are
    what keep a stalled socket from buffering the process down."""

    def check(report: Any) -> None:
        loris = report.extra.get("loris", [])
        if len(loris) != count:
            _fail(
                report,
                "slow_consumers_evicted",
                f"expected {count} loris subscribers, saw {len(loris)}",
            )
        for i, sub in enumerate(loris):
            if sub["evicted_reason"] != "slow_consumer":
                _fail(
                    report,
                    "slow_consumers_evicted",
                    f"loris {i} evicted_reason={sub['evicted_reason']!r}, "
                    "expected 'slow_consumer' — the hub let a stalled "
                    "socket keep buffering",
                )
            if sub["outbox_kinds"] != ["bye"]:
                _fail(
                    report,
                    "slow_consumers_evicted",
                    f"loris {i} outbox is {sub['outbox_kinds']} — eviction "
                    "must leave exactly one honest bye frame",
                )

    return check


def assert_failover(min_rejected: int = 1) -> Check:
    """Leader kill must fail over honestly: fencing strictly advances
    across the ledger's transitions, the zombie leader's generation-band
    writes are rejected (``min_rejected`` at least), and the replica
    ends the drill FRESH — fed by the new term."""

    def check(report: Any) -> None:
        replica = report.extra.get("replica")
        if replica is None:
            _fail(report, "failover", "no replica in a read-tier drill")
        fencings = [f for f in replica["fencings"] if f]
        if len(set(fencings)) < 2:
            _fail(
                report,
                "failover",
                f"fencing never advanced (ledger fencings: {fencings}) — "
                "no new leadership term was established",
            )
        if replica["rejected_stale"] < min_rejected:
            _fail(
                report,
                "failover",
                f"only {replica['rejected_stale']} zombie records "
                f"rejected, expected ≥{min_rejected} — split-brain writes "
                "reached the replica",
            )
        if replica["stale"]:
            _fail(
                report,
                "failover",
                "replica still stale at drill end — the new term never "
                "fed it",
            )

    return check


def assert_stale_paints_during_outage() -> Check:
    """While no leader is publishing, the replica's interactive paints
    must go DEGRADED (honest staleness at the HTTP layer) — and a shed
    must never stand in for a degrade."""

    def check(report: Any) -> None:
        if not report.counters.get("interactive_degraded"):
            _fail(
                report,
                "stale_paints_during_outage",
                "no interactive render degraded while the bus feed was "
                "silent — the replica claimed freshness it did not have",
            )

    return check


__all__ = [
    "Check",
    "assert_debug_sheds_first",
    "assert_failover",
    "assert_hub_honest",
    "assert_never_pages",
    "assert_no_stale_paints",
    "assert_pages_within",
    "assert_recovery_unpages",
    "assert_slow_consumers_evicted",
    "assert_stale_paints_during_outage",
    "assert_zero_5xx",
]
