"""Incident scenario engine (ADR-030).

Deterministic chaos drills: a declarative DSL (:mod:`.dsl`) scripts
inject/hold/recover phases on injected clocks, fault injectors
(:mod:`.inject`) break real seams, a runner (:mod:`.runner`) drives a
real in-process app (or an ADR-025 leader+replica pair) through the
drill recording an ADR-018 transcript, and response assertions
(:mod:`.assertions`) gate what the observability stack must DO about
each fault. The named drills live in :mod:`.catalog`; the merged
incident timeline they narrate is served at ``/debug/incidentz``
(:mod:`..obs.timeline`).
"""

from .catalog import SCENARIO_NAMES, all_scenarios, get_scenario
from .dsl import (
    Phase,
    ScenarioAssertionError,
    ScenarioError,
    ScenarioSpec,
)
from .runner import (
    ScenarioContext,
    ScenarioReport,
    ScenarioRunner,
    run_scenario,
)

__all__ = [
    "Phase",
    "SCENARIO_NAMES",
    "ScenarioAssertionError",
    "ScenarioContext",
    "ScenarioError",
    "ScenarioReport",
    "ScenarioRunner",
    "ScenarioSpec",
    "all_scenarios",
    "get_scenario",
    "run_scenario",
]
