"""Fault injectors (ADR-030): the *what* of a drill.

Each public function is an action factory: it returns a closure over
the runner's :class:`~.runner.ScenarioContext` suitable for a
:class:`~.dsl.Phase`'s ``enter``/``tick`` lists. Injectors break real
seams, not simulations of them:

- :class:`FaultTransport` wraps the app's transport at the same
  ``request(path, timeout_s)`` interface the ADR-014 pool and the
  ADR-018 :class:`~..history.record.RecordingTransport` decorate, so
  errors and latency hit every consumer above the seam (sync, metrics
  refresher, Prometheus probe chain) with no special casing;
- preemption waves push NotReady/DELETED events through the fixture
  fleet's :class:`~..transport.api_proxy.WatchFeed` — the same
  list+watch protocol a real apiserver speaks;
- hub restart / slow-loris act on the live :class:`~..push.hub
  .BroadcastHub`; leader kill acts on the live ADR-025 elector.

Latency is *scripted*: an injected-latency transport advances the
drill's fake clocks instead of sleeping (ADR-013), and SLO burn is fed
through the engine's own ``feed_latency``/``feed_error`` seams — the
exact reduction the instrument observers perform — with scripted
values, so the burn math is deterministic while everything downstream
(states, paging, shed, evictions) is the production code path.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

from ..obs import slo as slo_mod
from ..transport import ApiError

#: Message carried by every injected transport error — greppable in
#: logs and transcripts, and distinct from any real apiserver message.
INJECTED_ERROR = "injected fault (incident drill)"


class FaultTransport:
    """Transport decorator injecting errors and scripted latency.

    Delegates everything (including fixture attributes like
    ``node_feed``) to ``inner``; ``request`` consults the live fault
    flags per call so a phase action can flip them mid-drill. Latency
    "passes" by advancing the drill's injected clocks via ``advance``
    — never a sleep."""

    def __init__(
        self,
        inner: Any,
        *,
        advance: Callable[[float], None] | None = None,
    ) -> None:
        self.inner = inner
        self._advance = advance
        #: Fail matching requests with a 503 ApiError while True.
        self.failing = False
        #: Substrings selecting which paths the faults apply to; empty
        #: means every path.
        self.match: Tuple[str, ...] = ()
        #: Scripted seconds each matching request "takes".
        self.latency_s = 0.0
        self.requests = 0
        self.injected_errors = 0
        self.injected_latency_s = 0.0

    def _matches(self, path: str) -> bool:
        return not self.match or any(s in path for s in self.match)

    def request(self, path: str, timeout_s: float = 2.0) -> Any:
        self.requests += 1
        if self._matches(path):
            if self.latency_s and self._advance is not None:
                self._advance(self.latency_s)
                self.injected_latency_s += self.latency_s
            if self.failing:
                self.injected_errors += 1
                raise ApiError(path, INJECTED_ERROR, 503)
        return self.inner.request(path, timeout_s)

    def __getattr__(self, name: str) -> Any:
        return getattr(self.inner, name)


# -- transport faults --------------------------------------------------


def transport_errors(on: bool = True, match: Tuple[str, ...] = ()) -> Any:
    """Flip transport-level 503s on/off (the recover phase passes
    ``on=False``)."""

    def action(ctx: Any) -> None:
        ctx.transport.failing = on
        ctx.transport.match = tuple(match)
        fault = "transport_error" if on else "transport_recover"
        ctx.inject(fault, {"match": list(match)})

    return action


def transport_latency(latency_s: float, match: Tuple[str, ...] = ()) -> Any:
    """Give matching transport requests a scripted duration."""

    def action(ctx: Any) -> None:
        ctx.transport.latency_s = float(latency_s)
        ctx.transport.match = tuple(match)
        ctx.inject("transport_latency", {"latency_s": latency_s})

    return action


# -- SLO burn feeds ----------------------------------------------------


def slow_paints(route: str, latency_s: float, count: int) -> Any:
    """Tick action: feed ``count`` breaching paint latencies for
    ``route`` into the engine — the deterministic stand-in for the
    observer reduction of that many slow renders (see module doc)."""

    def action(ctx: Any) -> None:
        for _ in range(count):
            ctx.engine.feed_latency(
                slo_mod.REQUEST_DURATION, float(latency_s), {"route": route}
            )

    return action


def good_paints(route: str, count: int, latency_s: float = 0.05) -> Any:
    """Tick action: feed healthy paint latencies (the recover phase's
    traffic turning the burn back down)."""

    def action(ctx: Any) -> None:
        for _ in range(count):
            ctx.engine.feed_latency(
                slo_mod.REQUEST_DURATION, float(latency_s), {"route": route}
            )

    return action


# -- Prometheus flapping -----------------------------------------------


def prometheus_flap(route: str = "/tpu/metrics", bad_per_tick: int = 8) -> Any:
    """Tick action: alternate the Prometheus proxy between broken and
    healthy each tick — the half-dead scrape target. Odd ticks fail the
    proxy paths and feed breaching scrape latencies; even ticks restore
    it and feed healthy ones, so the burn rides the flap."""

    def action(ctx: Any) -> None:
        ctx.faults["flap_tick"] = ctx.faults.get("flap_tick", 0) + 1
        flapped_down = ctx.faults["flap_tick"] % 2 == 1
        ctx.transport.failing = flapped_down
        ctx.transport.match = ("prometheus",)
        if flapped_down:
            ctx.inject("prom_flap_down", {"tick": ctx.faults["flap_tick"]})
            for _ in range(bad_per_tick):
                ctx.engine.feed_latency(
                    slo_mod.REQUEST_DURATION, 5.0, {"route": route}
                )
        else:
            for _ in range(bad_per_tick // 2):
                ctx.engine.feed_latency(
                    slo_mod.REQUEST_DURATION, 0.05, {"route": route}
                )

    return action


# -- preemption wave ---------------------------------------------------


def preemption_wave(per_tick: int = 2) -> Any:
    """Tick action: preempt ``per_tick`` more TPU nodes — mark them
    NotReady and DELETE their pods through the fixture WatchFeeds, the
    same deltas a real preemption pushes through list+watch."""

    def action(ctx: Any) -> None:
        import copy

        node_feed = ctx.transport.node_feed
        pod_feed = ctx.transport.pod_feed
        preempted: set[str] = ctx.faults.setdefault("preempted", set())
        victims = []
        for item in node_feed._items.values():
            name = item["metadata"]["name"]
            labels = item["metadata"].get("labels", {})
            if "cloud.google.com/gke-tpu-accelerator" not in labels:
                continue
            if name in preempted:
                continue
            victims.append(item)
            if len(victims) >= per_tick:
                break
        for node in victims:
            name = node["metadata"]["name"]
            preempted.add(name)
            downed = copy.deepcopy(node)
            for cond in downed.get("status", {}).get("conditions", []):
                if cond.get("type") == "Ready":
                    cond["status"] = "False"
                    cond["reason"] = "NodePreempted"
            node_feed.push("MODIFIED", downed)
            for pod in list(pod_feed._items.values()):
                if pod.get("spec", {}).get("nodeName") == name:
                    pod_feed.push("DELETED", pod)
            ctx.inject("preemption", {"node": name})

    return action


def restore_fleet() -> Any:
    """Recover-phase enter action: bring every preempted node back
    Ready (pods stay gone — recovery restores capacity, not workloads,
    same as a real preemption wave ending)."""

    def action(ctx: Any) -> None:
        import copy

        node_feed = ctx.transport.node_feed
        preempted: set[str] = ctx.faults.get("preempted", set())
        for item in list(node_feed._items.values()):
            name = item["metadata"]["name"]
            if name not in preempted:
                continue
            restored = copy.deepcopy(item)
            for cond in restored.get("status", {}).get("conditions", []):
                if cond.get("type") == "Ready":
                    cond["status"] = "True"
                    cond["reason"] = "KubeletReady"
            node_feed.push("MODIFIED", restored)
        preempted.clear()
        ctx.inject("fleet_restore", {})

    return action


# -- push hub faults ---------------------------------------------------


def hub_restart(clients: int = 6) -> Any:
    """Enter action: restart the broadcast hub (a worker bounce) and
    stampede ``clients`` resumers at it with pre-restart Last-Event-IDs.
    The fresh hub retains no backlog, so the honest answer to every one
    of them is the full-paint fallback — never a fabricated partial
    delta history (ADR-021)."""

    def action(ctx: Any) -> None:
        from ..push.hub import BroadcastHub

        old = ctx.hub()
        last_gen = old.snapshot()["last_generation"]
        old.close(reason="shutdown")
        # ``hub_factory`` is the counterexample seam: the fires test
        # installs a hub subclass that fabricates resume history, and
        # the honesty assertion must catch it.
        factory = ctx.faults.get("hub_factory", BroadcastHub)
        fresh = factory(
            monotonic=ctx.mono,
            shed_check=ctx.policy.paging,
        )
        fresh.eviction_observers.append(ctx.timeline.eviction_observer)
        ctx.push.hub = fresh
        ctx.inject("hub_restart", {"pre_restart_generation": last_gen})
        herd = []
        for _ in range(int(clients)):
            sub = fresh.subscribe(
                ["fleet"], last_event_id=f"g{max(last_gen, 1)}"
            )
            herd.append(sub)
        ctx.faults["herd"] = herd
        ctx.inject("reconnect_herd", {"clients": len(herd)})

    return action


def slow_loris(subscribers: int = 2) -> Any:
    """Enter action: attach ``subscribers`` SSE clients that will never
    drain their outboxes — the slow-loris consumer. Kept in
    ``ctx.faults['loris']``; frame ticks fill their outboxes until the
    hub evicts them (reason ``slow_consumer``) with one honest ``bye``."""

    def action(ctx: Any) -> None:
        subs = [
            ctx.hub().subscribe(["fleet"], priority="interactive")
            for _ in range(int(subscribers))
        ]
        ctx.faults["loris"] = subs
        ctx.inject("slow_loris", {"subscribers": len(subs)})

    return action


def publish_frames(frames_per_tick: int = 24) -> Any:
    """Tick action: fan synthetic fleet frames through the hub — the
    steady churn that fills a non-draining outbox and keeps honest
    clients' resume cursors moving."""

    def action(ctx: Any) -> None:
        hub = ctx.hub()
        for _ in range(int(frames_per_tick)):
            ctx.faults["gen"] = ctx.faults.get("gen", 0) + 1
            gen = ctx.faults["gen"]
            hub.publish(gen, {"fleet": {"page": "fleet", "ops": [], "generation": gen}})

    return action


# -- clock skew --------------------------------------------------------


def clock_skew(step_s: float) -> Any:
    """Enter action: step the WALL clock by ``step_s`` (negative =
    backwards) while the monotonic clock keeps marching — the NTP
    correction / operator ``date`` mistake mid-scrape. Every TTL, burn
    window, and staleness probe runs on the monotonic clock (ADR-013),
    so nothing downstream may flinch; display stamps honestly jump."""

    def action(ctx: Any) -> None:
        ctx.wall.advance(float(step_s))
        ctx.inject("clock_skew", {"step_s": step_s})

    return action


# -- leader kill (read tier, ADR-025) ----------------------------------


def kill_leader() -> Any:
    """Enter action: the leader vanishes mid-churn — resign its lease
    (the crash-fast path; a TTL lapse plays out the same protocol) and
    stop publishing. The replica's feed goes stale; its standby elector
    takes over on a later tick."""

    def action(ctx: Any) -> None:
        fencing = ctx.leader_elector.fencing
        ctx.faults["dead_fencing"] = fencing
        ctx.leader_elector.resign()
        ctx.inject("leader_kill", {"fencing": fencing})

    return action


def leader_publish() -> Any:
    """Tick action: whichever elector currently holds the lease
    publishes one generation record to the replica — the healthy bus
    churn (and, post-failover, the new term's records whose fencing
    band outranks any zombie writes)."""

    def action(ctx: Any) -> None:
        ctx.publish_generation()

    return action


def standby_takeover() -> Any:
    """Tick action: tick the standby elector (production runs this on
    the renewal thread); on the tick that wins the lease the new term's
    fencing token floors the generation band."""

    def action(ctx: Any) -> None:
        was = ctx.standby_elector.is_leader
        now = ctx.standby_elector.tick()
        if now and not was:
            ctx.inject(
                "standby_elected", {"fencing": ctx.standby_elector.fencing}
            )

    return action


def stale_publish(generations: int = 1) -> Any:
    """Tick action: the deposed leader keeps publishing records in its
    OLD generation band — the split-brain writes fencing exists to
    reject. The replica must discard every one."""

    def action(ctx: Any) -> None:
        for _ in range(int(generations)):
            ctx.publish_generation(fencing=ctx.faults.get("dead_fencing", 1))

    return action


__all__ = [
    "FaultTransport",
    "INJECTED_ERROR",
    "clock_skew",
    "good_paints",
    "hub_restart",
    "kill_leader",
    "leader_publish",
    "preemption_wave",
    "prometheus_flap",
    "publish_frames",
    "restore_fleet",
    "slow_loris",
    "slow_paints",
    "stale_publish",
    "standby_takeover",
    "transport_errors",
    "transport_latency",
]
