"""Scenario runner (ADR-030): drive a real app through a drill.

The runner builds the SAME objects production serves with — a
:class:`~..server.app.DashboardApp` over the demo fixture transport
(plus, for ``read_tier`` specs, an ADR-025 leader/replica pair with
real electors over a shared lease), a fresh ADR-016 SLO engine, an
ADR-017 :class:`~..gateway.shed.ShedPolicy`, and the app's live push
hub — then walks the spec's phases on scripted clocks, firing each
phase's actions and a fixed per-tick traffic script through the
admission path (``policy.decide`` → ``degraded_scope`` →
``app.handle``; shed rulings synthesize the gateway's 503 without
paying a render, exactly as the gateway would).

Admission is driven directly rather than through
:class:`~..gateway.gateway.RenderGateway` because the gateway's render
pool is real threads — scheduling order would leak into the transcript.
The policy ruling, the degraded contextvar scope, and the handler are
the production code; only the thread hop is elided.

Determinism (ADR-013/018): both clocks are scripted; the drill's entire
request/ruling sequence is recorded through an ADR-018
:class:`~..history.record.Recorder` onto those clocks, so two runs of
one scenario produce byte-identical transcripts — pinned by
``tests/test_scenarios.py`` and replayed by ``bench.py --scenario``.

The engine swap: the app's metrics observers feed whatever
``slo_mod.engine()`` returns, so the runner installs its scripted-clock
engine via ``set_engine`` for the drill and restores the previous one
in a ``finally`` — the same discipline the SLO tests use.

``sabotage`` is the counterexample seam: tests pass a callable that
breaks one policy (shed disabled, a hub that fabricates resume history,
a wall-clocked staleness probe) after setup, proving each scenario
assertion actually FIRES against the misbehavior it guards (the
fires/clean discipline, ADR-015).
"""

from __future__ import annotations

import io
import json
from typing import Any, Callable, Mapping

from ..gateway.gateway import RenderGateway
from ..gateway.pool import PRIORITY_DEBUG, PRIORITY_INTERACTIVE
from ..gateway.shed import ShedPolicy, degraded_scope
from ..history.record import Recorder
from ..obs import slo as slo_mod
from ..obs.slo import SLOT_S, SLOEngine
from ..obs.timeline import IncidentTimeline
from .dsl import Phase, ScenarioAssertionError, ScenarioSpec
from .inject import FaultTransport

#: Fixed per-tick request script: two interactive paints, the metrics
#: page, one debug surface, one ops surface — every priority class
#: exercised every tick, so shed/degrade/untouchable all have evidence.
DEFAULT_TRAFFIC: tuple[str, ...] = (
    "/tpu",
    "/tpu/metrics",
    "/tpu",
    "/debug/traces",
    "/metricsz",
)

#: Read-tier traffic omits /tpu/metrics: a replica serves fleet pages
#: from applied records; the Prometheus proxy lives with the leader.
READ_TIER_TRAFFIC: tuple[str, ...] = (
    "/tpu",
    "/tpu",
    "/debug/traces",
    "/metricsz",
)


class ScriptedClock:
    """Callable fake clock; actions advance it, nothing sleeps."""

    def __init__(self, start: float) -> None:
        self.now = float(start)

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float) -> float:
        self.now += float(dt)
        return self.now


class ScenarioReport:
    """Everything a response assertion (or bench) reads off one run."""

    def __init__(self, name: str) -> None:
        self.name = name
        #: ADR-018 JSONL transcript of the full request/ruling sequence.
        self.transcript = ""
        #: Incident timeline events (the /debug/incidentz view).
        self.events: list[dict[str, Any]] = []
        #: (mono, states) per tick — the SLO trajectory.
        self.states_history: list[tuple[float, dict[str, str]]] = []
        self.counters: dict[str, int] = {}
        self.metrics: dict[str, Any] = {}
        self.extra: dict[str, Any] = {}
        #: ScenarioAssertionErrors from the spec's checks (empty = pass).
        self.failures: list[ScenarioAssertionError] = []

    @property
    def passed(self) -> bool:
        return not self.failures

    def first_event(
        self, source: str, kind: str, *, after: float | None = None
    ) -> dict[str, Any] | None:
        """Earliest timeline event matching (source, kind), optionally
        at-or-after a monotonic stamp. Ledger-merged events carry
        ``mono=None`` and never match an ``after`` filter."""
        for event in self.events:
            if event.get("source") != source or event.get("kind") != kind:
                continue
            if after is not None:
                mono = event.get("mono")
                if mono is None or mono < after:
                    continue
            return event
        return None


class ScenarioContext:
    """Mutable drill state handed to every phase action. Holds the real
    objects (app, engine, policy, hub accessor) plus the scripted
    clocks and a ``faults`` scratchpad the injectors coordinate
    through."""

    def __init__(
        self,
        spec: ScenarioSpec,
        *,
        start_mono: float = 1_000.0,
        start_wall: float = 1_700_000_000.0,
    ) -> None:
        from ..server.app import DashboardApp, make_demo_transport

        self.spec = spec
        self.mono = ScriptedClock(start_mono)
        self.wall = ScriptedClock(start_wall)
        self.faults: dict[str, Any] = {}
        self.transport = FaultTransport(
            make_demo_transport(), advance=self.mono.advance
        )
        self.app = DashboardApp(
            self.transport, clock=self.wall, monotonic=self.mono
        )
        self.push = self.app.push
        self.engine = SLOEngine(monotonic=self.mono)
        self.policy = ShedPolicy(monotonic=self.mono)
        self.timeline: IncidentTimeline = self.app.incidents
        self.policy.observers.append(self.timeline.gateway_observer)
        self.push.hub.eviction_observers.append(self.timeline.eviction_observer)
        self.recorder = Recorder(
            io.StringIO(),
            monotonic=self.mono,
            wall=self.wall,
            note=f"scenario:{spec.name}",
        )
        # Per-priority accounting the assertions read.
        self.counts = {
            "interactive_total": 0,
            "interactive_degraded": 0,
            "debug_total": 0,
            "debug_shed": 0,
            "ops_total": 0,
            "shed_503": 0,
            "non_shed_5xx": 0,
        }
        self.replica: Any = None
        self.leader_elector: Any = None
        self.standby_elector: Any = None
        if spec.read_tier:
            self._build_read_tier()

    def _build_read_tier(self) -> None:
        from ..replicate.leader import LeaderElector, LeaseStore
        from ..replicate.replica import ReplicaApp

        self.replica = ReplicaApp(
            clock=self.wall, monotonic=self.mono, stale_after_s=60.0
        )
        # The replica's timeline/ledger is the drill's: elector
        # transitions from BOTH electors land in the ledger the
        # /debug/incidentz merge reads (ADR-028's wall-merge rule).
        self.timeline = self.replica.incidents
        self.policy.observers = [self.timeline.gateway_observer]
        self.policy.degraded_probe = self.replica.stale
        store = LeaseStore(monotonic=self.mono)
        self.leader_elector = LeaderElector(
            store, "leader-0", ttl_s=600.0,
            monotonic=self.mono, ledger=self.replica.ledger,
        )
        self.standby_elector = LeaderElector(
            store, "replica-0", ttl_s=600.0,
            monotonic=self.mono, ledger=self.replica.ledger,
        )
        self.leader_elector.tick()
        # Prime the leader's snapshot (one real sync) and the replica's
        # feed (one accepted record) so the drill starts healthy.
        self.app.handle("/tpu")
        self.publish_generation()

    # -- accessors actions use -------------------------------------------

    def hub(self) -> Any:
        """The app's LIVE hub — re-read per call because the
        hub-restart injector replaces it mid-drill."""
        return self.push.hub

    def inject(self, fault: str, detail: Mapping[str, Any] | None = None) -> None:
        self.timeline.inject(self.spec.name, fault, detail)

    def install_engine(self, engine: Any) -> None:
        """Swap the drill's engine (the clock-skew counterexample
        installs a wall-clocked one). Re-points the global accessor so
        the app's observers and the policy follow."""
        self.engine = engine
        slo_mod.set_engine(engine)
        self.policy.invalidate()

    def publish_generation(self, *, fencing: int | None = None) -> bool:
        """Build one generation record off the leader app's snapshot
        and offer it to the replica, fenced into ``fencing``'s
        generation band (default: the live lease holder's)."""
        from ..replicate.bus import build_record
        from ..replicate.leader import generation_floor

        if fencing is None:
            for elector in (self.standby_elector, self.leader_elector):
                if elector is not None and elector.is_leader:
                    fencing = elector.fencing
                    break
        fencing = int(fencing or 1)
        seqs: dict[int, int] = self.faults.setdefault("pub_seq", {})
        seqs[fencing] = seqs.get(fencing, 0) + 1
        generation = generation_floor(fencing) + seqs[fencing]
        record = build_record(
            self.app._last_snapshot, generation=generation, fencing=fencing
        )
        return bool(self.replica.apply_record(record))

    # -- driving ----------------------------------------------------------

    def advance(self, dt: float) -> None:
        self.mono.advance(dt)
        self.wall.advance(dt)

    def request(self, path: str) -> int:
        """One request through the production admission path; the
        ruling and status land in the transcript."""
        target = self.replica if self.spec.read_tier else self.app
        route = target._route_label(path)
        priority = RenderGateway.classify(route)
        decision = self.policy.decide(route, priority)
        if priority == PRIORITY_INTERACTIVE:
            self.counts["interactive_total"] += 1
        elif priority == PRIORITY_DEBUG:
            self.counts["debug_total"] += 1
        else:
            self.counts["ops_total"] += 1
        if decision.shed:
            # The gateway's shed response, without paying the render.
            self.counts["debug_shed"] += 1
            self.counts["shed_503"] += 1
            self.recorder.record_ok(
                path, {"status": 503, "shed": True, "degraded": False}
            )
            return 503
        with degraded_scope(decision.degraded):
            status, _ctype, _body = target.handle(path)
        if decision.degraded:
            self.counts["interactive_degraded"] += 1
        if status >= 500:
            self.counts["non_shed_5xx"] += 1
        self.recorder.record_ok(
            path,
            {"status": status, "shed": False, "degraded": decision.degraded},
        )
        return status

    def traffic(self) -> None:
        script = self.spec.extra.get(
            "traffic",
            READ_TIER_TRAFFIC if self.spec.read_tier else DEFAULT_TRAFFIC,
        )
        for path in script:
            self.request(path)

    def sample(self) -> dict[str, str]:
        """One observability sample: refresh the policy's view of the
        engine (firing paging/restore observers) and diff SLO states
        onto the timeline."""
        states = dict(self.policy.states())
        self.timeline.sample_slo(states)
        return states


class ScenarioRunner:
    """Runs one spec: phases → ticks → report → checks."""

    def __init__(
        self,
        spec: ScenarioSpec,
        *,
        sabotage: Callable[[ScenarioContext], None] | None = None,
        start_mono: float = 1_000.0,
        start_wall: float = 1_700_000_000.0,
    ) -> None:
        self.spec = spec
        self.sabotage = sabotage
        self.start_mono = start_mono
        self.start_wall = start_wall

    def run(self) -> ScenarioReport:
        spec = self.spec
        previous_engine = slo_mod.engine()
        report = ScenarioReport(spec.name)
        try:
            ctx = ScenarioContext(
                spec, start_mono=self.start_mono, start_wall=self.start_wall
            )
            slo_mod.set_engine(ctx.engine)
            ctx.policy.invalidate()
            if self.sabotage is not None:
                self.sabotage(ctx)
            ctx.timeline.begin_drill(spec.name)
            for phase in spec.phases:
                ctx.timeline.set_phase(phase.kind)
                for action in phase.enter:
                    action(ctx)
                for _ in range(spec.ticks_in(phase)):
                    for action in phase.tick:
                        action(ctx)
                    ctx.traffic()
                    ctx.advance(spec.tick_s)
                    report.states_history.append((ctx.mono(), ctx.sample()))
            self._finalize(ctx, report)
            for check in spec.checks:
                try:
                    check(report)
                except ScenarioAssertionError as failure:
                    report.failures.append(failure)
            ctx.timeline.end_drill("passed" if report.passed else "failed")
            report.events = ctx.timeline.events()
        finally:
            slo_mod.set_engine(previous_engine)
        return report

    def _finalize(self, ctx: ScenarioContext, report: ScenarioReport) -> None:
        report.transcript = ctx.recorder._sink.getvalue()
        report.counters = dict(ctx.counts)
        report.events = ctx.timeline.events()
        self._drain_subscribers(ctx, report)
        if ctx.replica is not None:
            report.extra["replica"] = {
                "rejected_stale": ctx.replica.rejected_stale,
                "stale": bool(ctx.replica.stale()),
                "fencings": [
                    t.get("fencing", 0)
                    for t in ctx.replica.ledger.snapshot().get("transitions", [])
                ],
            }
        report.extra["hub"] = ctx.hub().snapshot()
        report.metrics.update(self._derive_metrics(ctx, report))

    def _drain_subscribers(self, ctx: ScenarioContext, report: ScenarioReport) -> None:
        herd = ctx.faults.get("herd") or []
        if herd:
            drained = []
            hub = ctx.hub()
            for sub in herd:
                kinds: list[dict[str, Any]] = []
                while True:
                    event = hub.poll(sub)
                    if event is None or event["kind"] == "heartbeat":
                        break
                    kinds.append(
                        {"kind": event["kind"], "data": event.get("data", {})}
                    )
                drained.append(kinds)
            report.extra["herd_events"] = drained
            report.extra["resume_fallbacks"] = ctx.hub().resume_fallbacks
        loris = ctx.faults.get("loris") or []
        if loris:
            report.extra["loris"] = [
                {
                    "evicted_reason": sub.evicted_reason,
                    "outbox_kinds": [e["kind"] for e in sub.outbox],
                }
                for sub in loris
            ]

    def _derive_metrics(
        self, ctx: ScenarioContext, report: ScenarioReport
    ) -> dict[str, Any]:
        counts = report.counters
        first_inject = report.first_event("scenario", "inject")
        first_page = report.first_event("gateway", "paging")
        metrics: dict[str, Any] = {
            "shed_rate_debug": (
                counts["debug_shed"] / counts["debug_total"]
                if counts["debug_total"]
                else 0.0
            ),
            "stale_paint_rate": (
                counts["interactive_degraded"] / counts["interactive_total"]
                if counts["interactive_total"]
                else 0.0
            ),
            "zero_5xx": counts["non_shed_5xx"] == 0,
            "windows_to_page": None,
            "recovery_windows": None,
        }
        if first_inject and first_page:
            metrics["windows_to_page"] = round(
                (first_page["mono"] - first_inject["mono"]) / SLOT_S, 2
            )
        recover = None
        for event in report.events:
            if (
                event.get("source") == "scenario"
                and event.get("kind") == "phase"
                and event.get("detail", {}).get("phase") == "recover"
            ):
                recover = event
                break
        if recover is not None and recover.get("mono") is not None:
            restore = report.first_event(
                "gateway", "restore", after=recover["mono"]
            )
            if restore is not None:
                metrics["recovery_windows"] = round(
                    (restore["mono"] - recover["mono"]) / SLOT_S, 2
                )
        if report.states_history:
            metrics["final_states"] = dict(report.states_history[-1][1])
        return metrics


def run_scenario(
    spec: ScenarioSpec,
    *,
    sabotage: Callable[[ScenarioContext], None] | None = None,
) -> ScenarioReport:
    """Run one drill; raise its first failed check (tests and the bench
    call this — a failing drill should fail loudly, with the scenario
    and check names in the message)."""
    report = ScenarioRunner(spec, sabotage=sabotage).run()
    if report.failures:
        raise report.failures[0]
    return report


__all__ = [
    "DEFAULT_TRAFFIC",
    "READ_TIER_TRAFFIC",
    "ScenarioContext",
    "ScenarioReport",
    "ScenarioRunner",
    "ScriptedClock",
    "run_scenario",
]
