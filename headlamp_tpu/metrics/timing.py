"""Shared fetch-stamp discipline for the metrics clients (ADR-019).

Both Prometheus clients (:mod:`.client`, :mod:`.intel_client`) used to
open-code the same pair: wall clock for the DISPLAYED ``fetched_at``
stamp, ``perf_counter`` for the MEASURED ``fetch_ms`` duration — never
mixed, because an NTP step mid-fetch would corrupt a wall-clock elapsed
but can only relabel a display timestamp (ADR-013 clock audit). This
helper is that pair in one place, and it additionally tags the active
request span with the measured duration so span waterfalls, flight
events, and profiler attribution all see the same fetch number the
snapshot reports.
"""

from __future__ import annotations

import time
from typing import Callable

from ..obs.trace import annotate


class FetchTimer:
    """Started at construction; :meth:`stamp` closes the measurement.

    >>> timer = FetchTimer(clock)
    >>> ...  # discovery + fan-out + join
    >>> fetched_at, fetch_ms = timer.stamp()
    """

    __slots__ = ("_clock", "_t0")

    def __init__(self, clock: Callable[[], float]) -> None:
        self._clock = clock
        self._t0 = time.perf_counter()

    def stamp(self) -> tuple[float, float]:
        """(fetched_at, fetch_ms) — wall stamp from the injected clock,
        duration from perf_counter, rounded the way every snapshot
        field is. Also annotates the innermost open ADR-013 span (a
        no-op outside a trace) so the trace and the snapshot can never
        disagree about what the fetch cost."""
        fetch_ms = round((time.perf_counter() - self._t0) * 1000, 1)
        annotate(fetch_ms=fetch_ms)
        return self._clock(), fetch_ms
