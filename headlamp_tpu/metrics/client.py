"""TPU Prometheus client: discovery, fan-out queries, chip-level join.

Mirrors the reference client's four-stage shape
(`/root/reference/src/api/metrics.ts:61-154`) with TPU content:

1. **Service discovery** — probe a candidate chain of Prometheus
   services through the apiserver service proxy with a trivial query
   (``query=1``), first responder wins (`metrics.ts:61-90`). The chain
   adds Google Managed Prometheus's in-cluster frontend to the three
   community-standard services. The winner is cached per transport
   (ADR-014): a warm request skips the probe chain entirely — the
   chain is up to 6 serial round trips, pure RTT waste once the
   answer is known — and the cache self-invalidates when the fan-out
   proves the cached service dead.
2. **Fan-out** — the logical TPU metrics are queried in parallel
   (`metrics.ts:101-116` does Promise.all; here the shared RTT-aware
   fan-out scheduler over the transport's keep-alive pool).
3. **Schema tolerance** — each *logical* metric (tensorcore
   utilization, HBM used/total, memory-bandwidth utilization, duty
   cycle) is a fallback chain of candidate series names, because the
   tpu-device-plugin and libtpu exporters disagree on naming and label
   schema (SURVEY.md §7 hard part (c)). First non-empty result wins.
4. **Join** — samples join into per-chip rows keyed on
   (node, accelerator_id), with an instance→node fallback map built
   from ``node_uname_info`` when samples carry only ``instance``
   (`metrics.ts:119-124`).

Returns ``None`` when no Prometheus is reachable (`metrics.ts:97-98`) —
pages render the guided "install kube-prometheus/GMP" box, never crash.
"""

from __future__ import annotations

import re
import time
import urllib.parse
import weakref
from dataclasses import dataclass, field
from typing import Any, Callable, Mapping

from ..obs.trace import span as _span
from ..transport.api_proxy import ApiError, Transport
from ..transport.pool import fanout, pool_of
from .timing import FetchTimer

# ---------------------------------------------------------------------------
# Service discovery
# ---------------------------------------------------------------------------

#: Candidate (namespace, service:port) pairs, probed in order. The chain
#: is a superset of the reference's (`metrics.ts:61-65` probes
#: kube-prometheus-stack-prometheus:9090, prometheus-operated:9090, and
#: prometheus:9090): it carries all three of those, adds the
#: prometheus-operator (prometheus-k8s) and Helm-chart
#: (prometheus-server) service names, and finishes with Google Managed
#: Prometheus's in-cluster query frontend — GMP is the default metrics
#: stack on the GKE clusters TPU fleets run on.
PROMETHEUS_SERVICES: tuple[tuple[str, str], ...] = (
    ("monitoring", "prometheus-k8s:9090"),
    ("monitoring", "kube-prometheus-stack-prometheus:9090"),
    ("monitoring", "prometheus-operated:9090"),
    ("monitoring", "prometheus:9090"),
    ("monitoring", "prometheus-server:80"),
    ("gmp-system", "frontend:9090"),
)


def _proxy_query_path(namespace: str, service: str, promql: str) -> str:
    """Apiserver service-proxy path for one instant query — the same
    route the reference uses (`metrics.ts:71-79`), so no direct network
    path to Prometheus is needed."""
    q = urllib.parse.quote(promql, safe="")
    return (
        f"/api/v1/namespaces/{namespace}/services/{service}"
        f"/proxy/api/v1/query?query={q}"
    )


def _proxy_range_path(
    namespace: str, service: str, promql: str, start: float, end: float, step_s: int
) -> str:
    """Service-proxy path for a range query (utilization history — feeds
    the forecaster; the reference has no range queries, its only
    windowing is the 5m rate() in PromQL `metrics.ts:106`)."""
    q = urllib.parse.quote(promql, safe="")
    return (
        f"/api/v1/namespaces/{namespace}/services/{service}"
        f"/proxy/api/v1/query_range?query={q}"
        f"&start={start:.0f}&end={end:.0f}&step={step_s}"
    )


def find_prometheus_path(
    transport: Transport, timeout_s: float = 2.0
) -> tuple[str, str] | None:
    """Probe the chain with ``query=1``; return the first working
    (namespace, service) or None. Always probes — use
    :func:`resolve_prometheus` on hot paths to amortize the chain."""
    for namespace, service in PROMETHEUS_SERVICES:
        try:
            data = transport.request(
                _proxy_query_path(namespace, service, "1"), timeout_s
            )
        except ApiError:
            continue
        if isinstance(data, Mapping) and data.get("status") == "success":
            return namespace, service
    return None


#: Discovered (namespace, service) per live transport. Weak keys: a
#: transport's cache entry dies with it, and tests' throwaway
#: MockTransports never accumulate. Positive results only — a cluster
#: with no Prometheus yet must keep getting re-probed (the app's own
#: metrics TTL bounds how often that happens).
_DISCOVERY_CACHE: "weakref.WeakKeyDictionary[Any, tuple[str, str]]" = (
    weakref.WeakKeyDictionary()
)


def cached_prometheus(transport: Transport) -> tuple[str, str] | None:
    """The cached discovery for ``transport``, without probing."""
    try:
        return _DISCOVERY_CACHE.get(transport)
    except TypeError:  # unhashable / non-weakrefable transport
        return None


def resolve_prometheus(
    transport: Transport, timeout_s: float = 2.0
) -> tuple[str, str] | None:
    """Cached :func:`find_prometheus_path`: the probe chain (up to 6
    serial round trips against a dark cluster) runs once per transport;
    every later call is a dict hit. :func:`invalidate_prometheus` drops
    the entry when the cached service stops answering (ADR-014)."""
    cached = cached_prometheus(transport)
    if cached is not None:
        return cached
    found = find_prometheus_path(transport, timeout_s)
    if found is not None:
        try:
            _DISCOVERY_CACHE[transport] = found
        except TypeError:
            pass
    return found


def invalidate_prometheus(transport: Transport) -> None:
    """Forget ``transport``'s cached discovery — next fetch re-probes."""
    try:
        _DISCOVERY_CACHE.pop(transport, None)
    except TypeError:
        pass


# ---------------------------------------------------------------------------
# Logical metrics and their candidate series
# ---------------------------------------------------------------------------

#: logical name -> candidate PromQL expressions, tried until one returns
#: a non-empty vector. Order: BASELINE.json's canonical names first, then
#: the GKE tpu-device-plugin's kubelet-style names, then libtpu exporter
#: variants.
LOGICAL_METRICS: dict[str, tuple[str, ...]] = {
    "tensorcore_utilization": (
        "tensorcore_utilization",
        "tpu_tensorcore_utilization",
        "kubernetes_io_node_accelerator_tensorcore_utilization",
    ),
    "memory_bandwidth_utilization": (
        "memory_bandwidth_utilization",
        "tpu_memory_bandwidth_utilization",
        "kubernetes_io_node_accelerator_memory_bandwidth_utilization",
    ),
    "hbm_bytes_used": (
        "hbm_bytes_used",
        "tpu_hbm_memory_usage_bytes",
        "memory_used{accelerator=~\"tpu.*\"}",
    ),
    "hbm_bytes_total": (
        "hbm_bytes_total",
        "tpu_hbm_memory_total_bytes",
        "memory_total{accelerator=~\"tpu.*\"}",
    ),
    "duty_cycle": (
        "duty_cycle{accelerator=~\"tpu.*\"}",
        "tpu_duty_cycle",
    ),
}

#: Instance→node mapping series, used when TPU samples carry only
#: ``instance`` (`metrics.ts:119-124` builds the same map from it).
NODE_MAP_QUERY = "node_uname_info"


# ---------------------------------------------------------------------------
# Batched scrape (ADR-015): matcher-joined instant queries
# ---------------------------------------------------------------------------

#: ``name`` or ``name{selector}`` — the only shapes our candidate
#: queries take. Anything fancier (functions, offsets) is unbatchable
#: and keeps its own request.
_SELECTOR_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?$")


def _parse_selector(promql: str) -> tuple[str, str] | None:
    """Split a simple series selector into (metric name, label selector
    body) — ``("duty_cycle", 'accelerator=~"tpu.*"')`` — or None when
    the expression is not a plain selector."""
    m = _SELECTOR_RE.match(promql)
    if m is None:
        return None
    return m.group(1), m.group(2) or ""


def batched_instant_queries(
    queries: list[str],
) -> list[tuple[str, dict[str, str]]]:
    """Union per-metric instant queries into matcher-joined batches:
    every candidate sharing a label selector collapses into ONE
    ``{__name__=~"a|b|c",selector}`` query, and the response demuxes
    back per metric by the ``__name__`` label. Our 16-query fan-out
    (15 candidates + node map) folds into 2 batches — the single
    biggest term in BENCH_r06's 28 HTTP requests per paint.

    Returns ``[(batched_promql, {series_name: original_promql})]`` in
    first-seen selector order; an unbatchable expression rides along as
    its own singleton batch so callers need no special case."""
    groups: dict[str, list[tuple[str, str]]] = {}
    order: list[str] = []
    out: list[tuple[str, dict[str, str]]] = []
    for promql in queries:
        parsed = _parse_selector(promql)
        if parsed is None:
            out.append((promql, {promql: promql}))
            continue
        name, selector = parsed
        if selector not in groups:
            groups[selector] = []
            order.append(selector)
        if all(name != n for n, _ in groups[selector]):
            groups[selector].append((name, promql))
    for selector in order:
        pairs = groups[selector]
        # Metric names are [a-zA-Z0-9_:] — no regex metacharacters —
        # so the alternation needs no escaping. Anchored: Prometheus
        # fully anchors __name__=~ itself.
        matcher = "__name__=~\"" + "|".join(n for n, _ in pairs) + "\""
        body = matcher + ("," + selector if selector else "")
        out.append(("{" + body + "}", {n: q for n, q in pairs}))
    return out


# ---------------------------------------------------------------------------
# Result model
# ---------------------------------------------------------------------------

@dataclass
class TpuChipMetrics:
    """One TPU chip's (or one host aggregate's) telemetry row — the
    analogue of ``GpuChipMetrics`` (`metrics.ts:21-32`). Fractions are
    normalized to 0-1; None means that series had no sample for this
    chip."""

    node: str
    accelerator_id: str
    tensorcore_utilization: float | None = None
    memory_bandwidth_utilization: float | None = None
    hbm_bytes_used: float | None = None
    hbm_bytes_total: float | None = None
    duty_cycle: float | None = None


@dataclass
class TpuMetricsSnapshot:
    """Everything the MetricsPage needs, including the honesty matrix:
    ``availability`` says which logical metrics actually returned data —
    rendered to the user exactly as the reference's Metric Availability
    section does (`MetricsPage.tsx:125-185`)."""

    namespace: str
    service: str
    chips: list[TpuChipMetrics] = field(default_factory=list)
    availability: dict[str, bool] = field(default_factory=dict)
    #: Which candidate expression satisfied each available metric —
    #: surfaced in diagnostics so operators know which exporter they run.
    resolved_series: dict[str, str] = field(default_factory=dict)
    fetched_at: float = 0.0
    #: Wall-clock cost of discovery + fan-out + join — the scrape→paint
    #: instrumentation the BASELINE <2s target is measured against
    #: (SURVEY.md §5 tracing carry-over).
    fetch_ms: float = 0.0

    @property
    def by_node(self) -> dict[str, list[TpuChipMetrics]]:
        out: dict[str, list[TpuChipMetrics]] = {}
        for chip in self.chips:
            out.setdefault(chip.node, []).append(chip)
        return out


# ---------------------------------------------------------------------------
# Fetch + join
# ---------------------------------------------------------------------------

def _vector_result(data: Any) -> list[Mapping[str, Any]]:
    """Extract a successful instant-query vector; anything else -> []."""
    if not isinstance(data, Mapping) or data.get("status") != "success":
        return []
    inner = data.get("data")
    if not isinstance(inner, Mapping) or inner.get("resultType") != "vector":
        return []
    result = inner.get("result")
    return [s for s in result if isinstance(s, Mapping)] if isinstance(result, list) else []


def _sample_value(sample: Mapping[str, Any]) -> float | None:
    value = sample.get("value")
    if not isinstance(value, (list, tuple)) or len(value) != 2:
        return None
    try:
        return float(value[1])
    except (TypeError, ValueError):
        return None


def _sample_labels(sample: Mapping[str, Any]) -> Mapping[str, str]:
    metric = sample.get("metric")
    return metric if isinstance(metric, Mapping) else {}


#: Label keys that may carry the node name, by exporter variant.
_NODE_LABELS = ("node", "node_name", "exported_node", "kubernetes_node")
#: Label keys that may carry the chip/accelerator identity.
_CHIP_LABELS = ("accelerator_id", "device", "chip", "tpu", "gpu")


def _node_of(labels: Mapping[str, str], instance_map: Mapping[str, str]) -> str:
    for key in _NODE_LABELS:
        if labels.get(key):
            return str(labels[key])
    instance = str(labels.get("instance", ""))
    if instance in instance_map:
        return instance_map[instance]
    # Strip the port: '10.0.0.7:9100' and '10.0.0.7:8431' are one host.
    host = instance.rsplit(":", 1)[0]
    return instance_map.get(host, host or "unknown")


def _chip_of(labels: Mapping[str, str]) -> str:
    for key in _CHIP_LABELS:
        if labels.get(key):
            return str(labels[key])
    return "0"


def _build_instance_map(samples: list[Mapping[str, Any]]) -> dict[str, str]:
    """instance (with and without port) -> nodename, from node_uname_info
    (`metrics.ts:119-124`)."""
    out: dict[str, str] = {}
    for s in samples:
        labels = _sample_labels(s)
        nodename = str(labels.get("nodename", ""))
        instance = str(labels.get("instance", ""))
        if nodename and instance:
            out[instance] = nodename
            out[instance.rsplit(":", 1)[0]] = nodename
    return out


_FRACTION_METRICS = (
    "tensorcore_utilization",
    "memory_bandwidth_utilization",
    "duty_cycle",
)

#: Per-series scale detection threshold. A genuine utilization fraction
#: is bounded by 1.0, so a sample clearly above it can only come from a
#: 0-100 exporter and the whole series is divided by 100 — including a
#: near-idle 0-100 series (max 1.3 ⇒ 1.3%) that the old >1.5 cutoff
#: left rendering as 130%. The margin above 1.0 is deliberately wide:
#: Prometheus ``rate()`` extrapolation can overshoot a saturated 0-1
#: chip past 1.0, and misreading that as percent would divide a
#: saturated fleet by 100 (hiding saturation) — a far worse error than
#: an idle percent-exporter in the residual (1.0, 1.2] band rendering
#: as the clamped 100% (see format_percent).
FRACTION_MAX = 1.2


def _strip_name_label(sample: Mapping[str, Any]) -> Mapping[str, Any]:
    """Demuxed sample minus its ``__name__`` label, for exact parity
    with what the corresponding per-metric query returns from the
    fixtures (the join itself never reads ``__name__``)."""
    labels = _sample_labels(sample)
    if "__name__" not in labels:
        return sample
    out = dict(sample)
    out["metric"] = {k: v for k, v in labels.items() if k != "__name__"}
    return out


def _fanout_batched(
    run_query: Callable[[str], list[Mapping[str, Any]]],
    queries: list[str],
    pool: Any,
) -> dict[str, list[Mapping[str, Any]]]:
    """Run the instant-query fan-out as matcher-joined batches, demuxing
    per-candidate samples by ``__name__``. Batching is an OPTIMIZATION,
    never a dependency (the Pallas policy): a batch that fails at the
    transport layer, returns non-success, or comes back EMPTY falls
    back to its member queries one by one — some frontends (GMP) are
    entitled to reject a cross-metric ``__name__`` regex, and an empty
    batch is indistinguishable from that rejection, so only the
    unbatched answer is treated as authoritative."""
    batches = batched_instant_queries(queries)
    batch_results = fanout.map(run_query, [b[0] for b in batches], pool=pool)
    results: dict[str, list[Mapping[str, Any]]] = {q: [] for q in queries}
    fallback: list[str] = []
    for (_, by_name), samples in zip(batches, batch_results):
        if not samples:
            fallback.extend(by_name.values())
            continue
        for sample in samples:
            target = by_name.get(str(_sample_labels(sample).get("__name__", "")))
            if target is not None:
                results[target].append(_strip_name_label(sample))
    if fallback:
        for q, r in zip(fallback, fanout.map(run_query, fallback, pool=pool)):
            results[q] = r
    return results


def fetch_tpu_metrics(
    transport: Transport,
    *,
    timeout_s: float = 2.0,
    clock: Callable[[], float] = time.time,
    prometheus: tuple[str, str] | None = None,
    batched: bool = True,
) -> TpuMetricsSnapshot | None:
    """Discover Prometheus (unless ``prometheus`` pins it; cached per
    transport otherwise), fan out all logical-metric candidate queries
    plus the node map over the transport's connection pool — as two
    matcher-joined batched queries by default (ADR-015), or one request
    per candidate with ``batched=False`` (the escape hatch and the
    parity baseline) — and join into per-chip rows. None when no
    Prometheus answers."""
    timer = FetchTimer(clock)
    # ADR-013 stage spans: discovery (the candidate-chain probe — the
    # whole chain times out serially against a dark cluster, which is
    # the pathological latency this span exists to expose; `cached`
    # marks the warm path that skips it) and the parallel fan-out below.
    from_cache = prometheus is None and cached_prometheus(transport) is not None
    with _span("metrics.discover", pinned=prometheus is not None, cached=from_cache):
        found = prometheus or resolve_prometheus(transport, timeout_s)
    if found is None:
        return None
    namespace, service = found

    transport_failures: list[str] = []
    issued: list[str] = []

    def run_query(promql: str) -> list[Mapping[str, Any]]:
        issued.append(promql)  # list.append is GIL-atomic
        try:
            data = transport.request(
                _proxy_query_path(namespace, service, promql), timeout_s
            )
        except ApiError:
            transport_failures.append(promql)
            return []
        return _vector_result(data)

    # Fan out: every candidate of every logical metric plus the node map
    # — batched into matcher-joined queries by default (two requests
    # instead of sixteen), or one parallel wave per candidate — so one
    # slow series costs max(latency), not sum(latency). Candidate order
    # still decides which result is used. The shared scheduler sizes
    # each wave from the pool's RTT stats: idle pooled sockets are free
    # width, extra sockets must earn their handshake (ADR-014).
    queries: list[str] = [NODE_MAP_QUERY]
    for candidates in LOGICAL_METRICS.values():
        queries.extend(candidates)
    pool = pool_of(transport)
    with _span(
        "metrics.fanout", queries=len(queries), service=service, batched=batched
    ):
        if batched:
            results = _fanout_batched(run_query, queries, pool)
        else:
            results = dict(zip(queries, fanout.map(run_query, queries, pool=pool)))

    if issued and len(transport_failures) == len(issued):
        # Every query actually issued (batched AND the per-metric
        # fallbacks) failed at the transport layer: the discovered
        # service is gone (rolled, rescheduled). Drop the cached
        # discovery so the next fetch re-probes the chain instead of
        # fanning out against a corpse forever.
        invalidate_prometheus(transport)

    instance_map = _build_instance_map(results[NODE_MAP_QUERY])

    chips: dict[tuple[str, str], TpuChipMetrics] = {}
    availability: dict[str, bool] = {}
    resolved: dict[str, str] = {}
    for logical, candidates in LOGICAL_METRICS.items():
        samples: list[Mapping[str, Any]] = []
        for promql in candidates:
            samples = results[promql]
            if samples:
                resolved[logical] = promql
                break
        availability[logical] = bool(samples)
        # Scale is decided ONCE per resolved series, mirroring the
        # range-query path (see fetch_utilization_history). A genuine
        # utilization *fraction* cannot exceed 1.0, so any sample above
        # FRACTION_MAX (1.0 plus rate-jitter allowance) proves a 0-100
        # exporter — including a near-idle one reporting 1.2 meaning
        # 1.2%. Only the (1.0, FRACTION_MAX] sliver stays ambiguous;
        # the render-time clamp in format_percent bounds that residue.
        scale = 1.0
        if logical in _FRACTION_METRICS and samples:
            values = [v for v in map(_sample_value, samples) if v is not None]
            if values and max(values) > FRACTION_MAX:
                scale = 100.0
        for sample in samples:
            labels = _sample_labels(sample)
            value = _sample_value(sample)
            if value is None:
                continue
            if logical in _FRACTION_METRICS:
                value = value / scale
            key = (_node_of(labels, instance_map), _chip_of(labels))
            row = chips.get(key)
            if row is None:
                row = chips[key] = TpuChipMetrics(node=key[0], accelerator_id=key[1])
            setattr(row, logical, value)

    ordered = sorted(chips.values(), key=lambda c: (c.node, c.accelerator_id))
    fetched_at, fetch_ms = timer.stamp()
    return TpuMetricsSnapshot(
        namespace=namespace,
        service=service,
        chips=ordered,
        availability=availability,
        resolved_series=resolved,
        fetched_at=fetched_at,
        fetch_ms=fetch_ms,
    )


# ---------------------------------------------------------------------------
# Utilization history (range queries) — forecaster input
# ---------------------------------------------------------------------------

@dataclass
class UtilizationHistory:
    """Aligned per-chip utilization traces: ``series[i]`` belongs to
    ``keys[i] = (node, accelerator_id)``; every row has ``n_samples``
    points ``step_s`` apart ending at ``end``. Gaps are forward-filled
    (Prometheus staleness already interpolates short ones)."""

    keys: list[tuple[str, str]]
    series: list[list[float]]
    step_s: int
    end: float
    resolved_query: str


#: Minimum fraction of grid points a trace must actually have before it
#: is used for forecasting — forward-filling a handful of fresh samples
#: into a full window would fabricate history (the honesty analogue of
#: the reference's '≥5m of scrape history' hint, `MetricsPage.tsx:105`).
MIN_REAL_SAMPLE_FRACTION = 0.5


def fetch_utilization_history(
    transport: Transport,
    *,
    prometheus: tuple[str, str],
    window_s: int = 3600,
    step_s: int = 60,
    timeout_s: float = 2.0,
    clock: Callable[[], float] = time.time,
    preferred_query: str | None = None,
) -> UtilizationHistory | None:
    """One range query per candidate series until one returns usable
    data. ``preferred_query`` (e.g. the instant fetch's
    ``resolved_series['tensorcore_utilization']``) is tried first so a
    page view doesn't re-walk candidates the instant path already
    eliminated. None when no candidate has enough real history."""
    namespace, service = prometheus
    # Wall clock ON PURPOSE (clock-skew audit, ADR-013): start/end are
    # Prometheus range-query bounds — epoch timestamps the server
    # interprets — not elapsed-time math. Monotonic belongs to
    # durations (fetch_ms uses perf_counter); never to these.
    end = clock()
    start = end - window_s
    n_samples = int(window_s // step_s) + 1
    min_real = max(3, int(n_samples * MIN_REAL_SAMPLE_FRACTION))

    # Node-name join map, same as the instant path (`metrics.ts:119-124`)
    # — forecast rows must key identically to the chip cards beside them.
    instance_map: dict[str, str] = {}
    try:
        data = transport.request(
            _proxy_query_path(namespace, service, NODE_MAP_QUERY), timeout_s
        )
        instance_map = _build_instance_map(_vector_result(data))
    except ApiError:
        pass

    candidates = list(
        LOGICAL_METRICS["tensorcore_utilization"] + LOGICAL_METRICS["duty_cycle"]
    )
    if preferred_query and preferred_query in candidates:
        candidates.remove(preferred_query)
        candidates.insert(0, preferred_query)

    for promql in candidates:
        try:
            data = transport.request(
                _proxy_range_path(namespace, service, promql, start, end, step_s),
                timeout_s,
            )
        except ApiError:
            continue
        if not isinstance(data, Mapping) or data.get("status") != "success":
            continue
        inner = data.get("data")
        if not isinstance(inner, Mapping) or inner.get("resultType") != "matrix":
            continue
        result = inner.get("result")
        if not isinstance(result, list) or not result:
            continue

        keys: list[tuple[str, str]] = []
        series: list[list[float]] = []
        for entry in result:
            if not isinstance(entry, Mapping):
                continue
            labels = _sample_labels(entry)
            key = (_node_of(labels, instance_map), _chip_of(labels))
            values = entry.get("values")
            if not isinstance(values, list):
                continue
            # Align onto the fixed grid, forward-filling short gaps.
            by_ts = {}
            for v in values:
                if isinstance(v, (list, tuple)) and len(v) == 2:
                    try:
                        by_ts[round(float(v[0]))] = float(v[1])
                    except (TypeError, ValueError):
                        continue
            if len(by_ts) < min_real:
                continue  # mostly-fabricated trace: skip, stay honest
            # Scale is decided ONCE per series: normalizing per sample
            # would mix scales within one trace from a 0-100 exporter
            # (an idle 0.9% sample passing the threshold unscaled while
            # busy samples get divided), fabricating saturation. Same
            # FRACTION_MAX rule as the instant path: fractions cannot
            # exceed 1.0, so anything above it proves a 0-100 exporter.
            scale = 100.0 if max(by_ts.values()) > FRACTION_MAX else 1.0
            grid: list[float] = []
            last = next(iter(by_ts.values()))
            for i in range(n_samples):
                ts = round(start + i * step_s)
                last = by_ts.get(ts, last)
                grid.append(last / scale)
            keys.append(key)
            series.append(grid)
        if series:
            return UtilizationHistory(
                keys=keys,
                series=series,
                step_s=step_s,
                end=end,
                resolved_query=promql,
            )
    return None
