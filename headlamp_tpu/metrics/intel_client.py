"""Intel GPU Prometheus client — i915 hwmon power telemetry.

A faithful capability port of the reference's metrics client
(`/root/reference/src/api/metrics.ts:96-159`) into this framework's
transport: the same four queries (chip discovery, 5-minute energy rate
→ power W, TDP, instance→node map) joined on (chip, instance), sharing
the TPU client's service-discovery chain. The well-known availability
facts the reference documents in its UI (`MetricsPage.tsx:4-27`) are
encoded in :data:`INTEL_METRIC_AVAILABILITY`: frequency/utilization and
iGPU power are NOT obtainable from a standard node-exporter setup.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable

from ..transport.api_proxy import ApiError, Transport
from ..transport.pool import fanout, pool_of
from .client import (
    _build_instance_map,
    _node_of,
    _proxy_query_path,
    _sample_labels,
    _sample_value,
    _vector_result,
    resolve_prometheus,
)
from .timing import FetchTimer

#: The reference's PromQL set (`metrics.ts:101-116`). The power rate
#: needs ≥5m of scrape history before it returns data — the UI hint at
#: `MetricsPage.tsx:105` carries over.
INTEL_QUERIES = {
    "chips": 'node_hwmon_chip_names{chip_name="i915"}',
    "power": (
        "rate(node_hwmon_energy_joule_total[5m]) "
        '* on(chip,instance) group_left(chip_name) '
        'node_hwmon_chip_names{chip_name="i915"}'
    ),
    "tdp": (
        "node_hwmon_power_max_watt "
        '* on(chip,instance) group_left(chip_name) '
        'node_hwmon_chip_names{chip_name="i915"}'
    ),
    "node_map": "node_uname_info",
}

#: What a standard node-exporter i915 hwmon setup can and cannot
#: provide (`MetricsPage.tsx:125-185` renders exactly this honesty).
INTEL_METRIC_AVAILABILITY = (
    ("Package power (W)", True, "rate of node_hwmon_energy_joule_total, discrete i915"),
    ("TDP / power limit (W)", True, "node_hwmon_power_max_watt"),
    ("GPU frequency", False, "node-exporter's drm collector is AMD-only"),
    ("GPU utilization %", False, "needs intel-gpu-exporter / XPU manager"),
    ("Integrated GPU power", False, "iGPU shares the package sensor"),
)


@dataclass
class GpuChipMetrics:
    """One discrete i915 chip (`metrics.ts:21-32`)."""

    node: str
    chip: str
    power_watts: float | None = None
    tdp_watts: float | None = None

    @property
    def power_fraction(self) -> float | None:
        if self.power_watts is None or not self.tdp_watts:
            return None
        return self.power_watts / self.tdp_watts


@dataclass
class IntelMetricsSnapshot:
    namespace: str
    service: str
    chips: list[GpuChipMetrics] = field(default_factory=list)
    fetched_at: float = 0.0
    fetch_ms: float = 0.0


def format_watts(watts: float | None) -> str:
    """(`metrics.ts:161-164`)."""
    if watts is None:
        return "—"
    return f"{watts:.1f} W"


def fetch_intel_gpu_metrics(
    transport: Transport,
    *,
    timeout_s: float = 2.0,
    clock: Callable[[], float] = time.time,
    prometheus: tuple[str, str] | None = None,
) -> IntelMetricsSnapshot | None:
    """Discover (shared chain, cached per transport — ADR-014) then run
    the 4 queries in parallel over the transport's connection pool and
    join per (node, chip). None when no Prometheus answers
    (`metrics.ts:97-98`)."""
    timer = FetchTimer(clock)
    found = prometheus or resolve_prometheus(transport, timeout_s)
    if found is None:
        return None
    namespace, service = found

    def run_query(promql: str) -> list[Any]:
        try:
            data = transport.request(
                _proxy_query_path(namespace, service, promql), timeout_s
            )
        except ApiError:
            return []
        return _vector_result(data)

    names = list(INTEL_QUERIES)
    results = dict(
        zip(
            names,
            fanout.map(
                run_query,
                [INTEL_QUERIES[n] for n in names],
                pool=pool_of(transport),
            ),
        )
    )

    instance_map = _build_instance_map(results["node_map"])

    # One shared instance→node join with the TPU client (_node_of) so
    # both providers key chips identically under identical failures.
    chips: dict[tuple[str, str], GpuChipMetrics] = {}
    for sample in results["chips"]:
        labels = _sample_labels(sample)
        key = (_node_of(labels, instance_map), str(labels.get("chip", "?")))
        chips.setdefault(key, GpuChipMetrics(node=key[0], chip=key[1]))
    for field_name, result_key in (("power_watts", "power"), ("tdp_watts", "tdp")):
        for sample in results[result_key]:
            labels = _sample_labels(sample)
            value = _sample_value(sample)
            if value is None:
                continue
            key = (_node_of(labels, instance_map), str(labels.get("chip", "?")))
            row = chips.setdefault(key, GpuChipMetrics(node=key[0], chip=key[1]))
            setattr(row, field_name, value)

    # Clock discipline (wall stamp vs perf_counter duration) lives in
    # the shared FetchTimer — see metrics/timing.py.
    fetched_at, fetch_ms = timer.stamp()
    return IntelMetricsSnapshot(
        namespace=namespace,
        service=service,
        chips=sorted(chips.values(), key=lambda c: (c.node, c.chip)),
        fetched_at=fetched_at,
        fetch_ms=fetch_ms,
    )
