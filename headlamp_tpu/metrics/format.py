"""Display formatters for metric values.

The TPU analogue of the reference's ``formatWatts``/``formatPercent``
(`/root/reference/src/api/metrics.ts:161-168`): tiny, total functions the
pages and tests share.
"""

from __future__ import annotations


def format_percent(fraction: float | None, digits: int = 1) -> str:
    """0.874 -> '87.4%'. None (metric unavailable) -> '—'. Values already
    in percent (>1.5) are assumed pre-scaled — the tpu-device-plugin and
    libtpu exporters disagree on 0-1 vs 0-100 scaling, so the formatter
    normalizes rather than trusting either. The result is clamped to
    [0, 100]: every caller formats a utilization/duty-cycle fraction,
    which cannot legitimately exceed 100%. The clamp only bounds the
    residue the client's per-series scale detection (FRACTION_MAX in
    metrics.client) cannot resolve — rate jitter fractionally above 1.0
    — so nothing real is hidden by it."""
    if fraction is None:
        return "—"
    pct = fraction * 100 if fraction <= 1.5 else fraction
    pct = min(max(pct, 0.0), 100.0)
    return f"{pct:.{digits}f}%"


def normalize_fraction(value: float | None) -> float | None:
    """Scale-tolerant 0-1 normalization (0-100 inputs divided down)."""
    if value is None:
        return None
    return value / 100 if value > 1.5 else value


_BYTE_UNITS = ("B", "KiB", "MiB", "GiB", "TiB", "PiB")


def format_bytes(n: float | None) -> str:
    """16106127360 -> '15.0 GiB'. None -> '—'."""
    if n is None:
        return "—"
    value = float(n)
    for unit in _BYTE_UNITS:
        if abs(value) < 1024 or unit == _BYTE_UNITS[-1]:
            if unit == "B":
                return f"{int(value)} B"
            return f"{value:.1f} {unit}"
        value /= 1024
    return f"{value:.1f} PiB"


def format_ratio_bar(used: float | None, total: float | None) -> str:
    """'12.3 GiB / 15.8 GiB (78%)' — the HBM usage line."""
    if used is None or total is None or total <= 0:
        return "—"
    pct = round(used / total * 100)
    return f"{format_bytes(used)} / {format_bytes(total)} ({pct}%)"
