"""Metrics layer — Prometheus client for TPU telemetry.

Role-equivalent to the reference's metrics client
(`/root/reference/src/api/metrics.ts`): service discovery over a
candidate chain, parallel PromQL queries through the kube-apiserver
service proxy, sample joining, and honest availability reporting. The
i915 hwmon power series are replaced by tpu-device-plugin / libtpu
series (BASELINE north-star: tensorcore_utilization,
memory_bandwidth_utilization, hbm_bytes_used).
"""

from .client import (
    LOGICAL_METRICS,
    PROMETHEUS_SERVICES,
    TpuChipMetrics,
    TpuMetricsSnapshot,
    UtilizationHistory,
    cached_prometheus,
    fetch_tpu_metrics,
    fetch_utilization_history,
    find_prometheus_path,
    invalidate_prometheus,
    resolve_prometheus,
)
from .format import format_bytes, format_percent, format_ratio_bar

__all__ = [
    "LOGICAL_METRICS",
    "PROMETHEUS_SERVICES",
    "TpuChipMetrics",
    "TpuMetricsSnapshot",
    "UtilizationHistory",
    "cached_prometheus",
    "fetch_tpu_metrics",
    "fetch_utilization_history",
    "find_prometheus_path",
    "format_bytes",
    "format_percent",
    "format_ratio_bar",
    "invalidate_prometheus",
    "resolve_prometheus",
]
