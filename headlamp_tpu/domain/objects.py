"""Provider-neutral Kubernetes object helpers.

All cluster data enters the framework as plain JSON-shaped dicts (the same
contract the TS plugin gets from Headlamp's ApiProxy after jsonData
unwrapping). These helpers are total: any malformed input yields a neutral
value rather than raising, mirroring the boundary-validation discipline of
the reference domain layer (`/root/reference/src/api/k8s.ts:125-131`).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Mapping


from collections.abc import Mapping as _AbcMapping


def _is_mapping(value: Any) -> bool:
    """dict fast path first: all real API data is plain dicts, and an
    abc-Mapping isinstance is ~10x the cost of the exact-type check —
    measurable at fleet scale (hundreds of thousands of calls per
    dashboard paint)."""
    return type(value) is dict or isinstance(value, _AbcMapping)


def _as_mapping(value: Any) -> Mapping[str, Any]:
    return value if _is_mapping(value) else {}


def metadata(obj: Any) -> Mapping[str, Any]:
    return _as_mapping(_as_mapping(obj).get("metadata"))


def name(obj: Any) -> str:
    return str(metadata(obj).get("name", ""))


def namespace(obj: Any) -> str:
    return str(metadata(obj).get("namespace", ""))


def uid(obj: Any) -> str:
    return str(metadata(obj).get("uid", ""))


def labels(obj: Any) -> Mapping[str, str]:
    return _as_mapping(metadata(obj).get("labels"))


def creation_timestamp(obj: Any) -> str | None:
    ts = metadata(obj).get("creationTimestamp")
    return str(ts) if ts else None


def status(obj: Any) -> Mapping[str, Any]:
    return _as_mapping(_as_mapping(obj).get("status"))


def spec(obj: Any) -> Mapping[str, Any]:
    return _as_mapping(_as_mapping(obj).get("spec"))


# ---------------------------------------------------------------------------
# Node helpers
# ---------------------------------------------------------------------------

def node_capacity(node: Any) -> Mapping[str, Any]:
    return _as_mapping(status(node).get("capacity"))


def node_allocatable(node: Any) -> Mapping[str, Any]:
    return _as_mapping(status(node).get("allocatable"))


def _has_ready_condition(obj: Any) -> bool:
    conditions = status(obj).get("conditions")
    if not isinstance(conditions, list):
        return False
    return any(
        _is_mapping(c) and c.get("type") == "Ready" and c.get("status") == "True"
        for c in conditions
    )


def is_node_ready(node: Any) -> bool:
    """Ready condition check (reference: k8s.ts:329-331)."""
    return _has_ready_condition(node)


def node_info(node: Any) -> Mapping[str, Any]:
    return _as_mapping(status(node).get("nodeInfo"))


# ---------------------------------------------------------------------------
# Pod helpers
# ---------------------------------------------------------------------------

def pod_phase(pod: Any) -> str:
    return str(status(pod).get("phase") or "Unknown")


def pod_node_name(pod: Any) -> str | None:
    node = spec(pod).get("nodeName")
    return str(node) if node else None


def pod_containers(pod: Any, include_init: bool = True) -> list[Mapping[str, Any]]:
    """All container specs, optionally including initContainers — the same
    union the reference scans for resource requests (k8s.ts:250-264)."""
    s = spec(pod)
    out: list[Mapping[str, Any]] = []
    for key in ("containers", "initContainers") if include_init else ("containers",):
        items = s.get(key)
        if isinstance(items, list):
            out.extend(c for c in items if _is_mapping(c))
    return out


def pod_init_containers(pod: Any) -> list[Mapping[str, Any]]:
    items = spec(pod).get("initContainers")
    return [c for c in items if _is_mapping(c)] if isinstance(items, list) else []


def container_requests(container: Mapping[str, Any]) -> Mapping[str, Any]:
    return _as_mapping(_as_mapping(container.get("resources")).get("requests"))


def container_limits(container: Mapping[str, Any]) -> Mapping[str, Any]:
    return _as_mapping(_as_mapping(container.get("resources")).get("limits"))


def is_pod_ready(pod: Any) -> bool:
    return _has_ready_condition(pod)


def pod_resource_keys(pod: Any) -> set[str]:
    """Union of requests∪limits resource names over every container
    (init included). One pass feeds every provider's pod detection in
    classify_fleet — each provider re-walking the container list was
    the sync path's hottest loop at fleet scale."""
    keys: set[str] = set()
    for c in pod_containers(pod):
        keys.update(container_requests(c))
        keys.update(container_limits(c))
    return keys


def pod_restarts(pod: Any) -> int:
    """Total container restart count (reference: k8s.ts:307-309)."""
    statuses = status(pod).get("containerStatuses")
    if not isinstance(statuses, list):
        return 0
    total = 0
    for c in statuses:
        if _is_mapping(c):
            total += parse_int(c.get("restartCount"))
    return total


# ---------------------------------------------------------------------------
# Scalar parsing / formatting
# ---------------------------------------------------------------------------

def parse_int(value: Any) -> int:
    """Lenient integer parse: ints, numeric strings, floats; else 0.

    Matches the `parseInt(v, 10) || 0` idiom used throughout the reference
    (k8s.ts:177, k8s.ts:296).
    """
    if isinstance(value, bool):
        return int(value)
    if isinstance(value, int):
        return value
    if isinstance(value, float):
        return int(value)
    if isinstance(value, str):
        digits = ""
        stripped = value.strip()
        for i, ch in enumerate(stripped):
            if ch.isdigit() or (i == 0 and ch in "+-"):
                digits += ch
            else:
                break
        try:
            return int(digits)
        except ValueError:
            return 0
    return 0


def is_kube_list(value: Any) -> bool:
    """List-envelope guard (reference: k8s.ts:320-323)."""
    return _is_mapping(value) and isinstance(value.get("items"), list)


def kube_list_items(value: Any) -> list[Any]:
    return list(value["items"]) if is_kube_list(value) else []


def dedup_by_uid(objs: Iterable[Any]) -> list[Any]:
    """Drop objects with duplicate (or missing) UIDs, preserving order —
    the multi-selector merge used for plugin daemon pods
    (`/root/reference/src/api/IntelGpuDataContext.tsx:168-174`)."""
    seen: set[str] = set()
    out = []
    for o in objs:
        u = uid(o)
        if not u or u in seen:
            continue
        seen.add(u)
        out.append(o)
    return out


def allocation_summary(
    nodes: Iterable[Any],
    pods: Iterable[Any],
    capacity_fn: Callable[[Any], int],
    allocatable_fn: Callable[[Any], int],
    request_fn: Callable[[Any], int],
) -> dict[str, int]:
    """Capacity/allocatable from nodes; in-use from Running pods' device
    requests — the OverviewPage allocation summary
    (`/root/reference/src/components/OverviewPage.tsx:88-116`),
    parameterized over a provider's counting functions so TPU and Intel
    share one implementation."""
    capacity = sum(capacity_fn(n) for n in nodes)
    allocatable = sum(allocatable_fn(n) for n in nodes)
    in_use = sum(request_fn(p) for p in pods if pod_phase(p) == "Running")
    pct = round(in_use / capacity * 100) if capacity > 0 else 0
    return {
        "capacity": capacity,
        "allocatable": allocatable,
        "in_use": in_use,
        "free": allocatable - in_use,
        "utilization_pct": pct,
    }


def count_pod_phases(pods: Iterable[Any]) -> dict[str, int]:
    """Phase histogram with an Other bucket (`OverviewPage.tsx:122-130`).
    Provider-neutral: both the TPU and Intel overview/pods pages consume
    it."""
    counts = {"Running": 0, "Pending": 0, "Succeeded": 0, "Failed": 0, "Other": 0}
    for p in pods:
        phase = pod_phase(p)
        counts[phase if phase in counts else "Other"] += 1
    return counts


def format_age(timestamp: str | None, now_epoch_s: float) -> str:
    """Human age from an RFC3339 timestamp: s/m/h/d buckets
    (reference: k8s.ts:337-348). ``now_epoch_s`` is explicit so callers and
    tests control the clock."""
    if not timestamp:
        return "unknown"
    import datetime

    try:
        ts = timestamp.replace("Z", "+00:00")
        then = datetime.datetime.fromisoformat(ts).timestamp()
    except ValueError:
        return "unknown"
    secs = int(now_epoch_s - then)
    if secs < 0:
        secs = 0
    if secs < 60:
        return f"{secs}s"
    mins = secs // 60
    if mins < 60:
        return f"{mins}m"
    hours = mins // 60
    if hours < 24:
        return f"{hours}h"
    return f"{hours // 24}d"
