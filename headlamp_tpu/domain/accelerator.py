"""Provider-agnostic accelerator abstraction.

The reference hard-wires one provider into its context
(`/root/reference/src/api/IntelGpuDataContext.tsx`); the BASELINE
north-star lifts that into an AcceleratorDataContext where TPU and Intel
GPU coexist and degrade independently. This module is the pure core of
that abstraction: a Provider describes how to detect its nodes/pods and
count devices; ``classify_fleet`` partitions one cluster snapshot into
per-provider views in a single pass.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Mapping

from . import intel, objects, tpu


@dataclass(frozen=True)
class Provider:
    """One accelerator family. ``device_unit`` is the display word for a
    schedulable device ('chip' for TPU, 'device' for Intel GPU)."""

    name: str
    display_name: str
    device_unit: str
    is_accel_node: Callable[[Any], bool]
    is_accel_pod: Callable[[Any], bool]
    is_plugin_pod: Callable[[Any], bool]
    node_device_capacity: Callable[[Any], int]
    node_device_allocatable: Callable[[Any], int]
    pod_device_request: Callable[[Any], int]


TPU_PROVIDER = Provider(
    name="tpu",
    display_name="Cloud TPU",
    device_unit="chip",
    is_accel_node=tpu.is_tpu_node,
    is_accel_pod=tpu.is_tpu_requesting_pod,
    is_plugin_pod=tpu.is_tpu_plugin_pod,
    node_device_capacity=tpu.get_node_chip_capacity,
    node_device_allocatable=tpu.get_node_chip_allocatable,
    pod_device_request=tpu.get_pod_chip_request,
)

INTEL_PROVIDER = Provider(
    name="intel",
    display_name="Intel GPU",
    device_unit="device",
    is_accel_node=intel.is_intel_gpu_node,
    is_accel_pod=intel.is_gpu_requesting_pod,
    is_plugin_pod=intel.is_intel_plugin_pod,
    node_device_capacity=intel.get_node_gpu_count,
    node_device_allocatable=intel.get_node_gpu_allocatable,
    pod_device_request=intel.get_pod_device_request,
)

#: Registration order = sidebar/priority order. TPU first by design.
PROVIDERS: tuple[Provider, ...] = (TPU_PROVIDER, INTEL_PROVIDER)


@dataclass
class FleetView:
    """One provider's slice of a cluster snapshot."""

    provider: Provider
    nodes: list[Any] = field(default_factory=list)
    pods: list[Any] = field(default_factory=list)
    plugin_pods: list[Any] = field(default_factory=list)

    @property
    def plugin_installed(self) -> bool:
        """Plugin presence = daemon pods seen OR devices advertised. The
        TPU side has no operator CRD, so — per the reference's own
        CRD-absent fallback (ADR-003) — allocatable devices are accepted
        as installation evidence."""
        if self.plugin_pods:
            return True
        return any(self.provider.node_device_allocatable(n) > 0 for n in self.nodes)

    def allocation_summary(self) -> Mapping[str, int]:
        return objects.allocation_summary(
            self.nodes,
            self.pods,
            self.provider.node_device_capacity,
            self.provider.node_device_allocatable,
            self.provider.pod_device_request,
        )


def classify_fleet(
    nodes: Iterable[Any],
    pods: Iterable[Any],
    providers: tuple[Provider, ...] = PROVIDERS,
) -> dict[str, FleetView]:
    """Partition a cluster snapshot into per-provider views in one pass
    over nodes and one over pods (a node or pod can belong to several
    providers only in pathological fixtures; each provider applies its own
    guard independently, so nothing is double-hidden)."""
    views = {p.name: FleetView(provider=p) for p in providers}
    for n in nodes:
        for p in providers:
            if p.is_accel_node(n):
                views[p.name].nodes.append(n)
    for pod in pods:
        for p in providers:
            if p.is_accel_pod(pod):
                views[p.name].pods.append(pod)
            if p.is_plugin_pod(pod):
                views[p.name].plugin_pods.append(pod)
    return views
