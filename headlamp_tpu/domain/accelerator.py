"""Provider-agnostic accelerator abstraction.

The reference hard-wires one provider into its context
(`/root/reference/src/api/IntelGpuDataContext.tsx`); the BASELINE
north-star lifts that into an AcceleratorDataContext where TPU and Intel
GPU coexist and degrade independently. This module is the pure core of
that abstraction: a Provider describes how to detect its nodes/pods and
count devices; ``classify_fleet`` partitions one cluster snapshot into
per-provider views in a single pass.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Mapping

from . import intel, objects, tpu


@dataclass(frozen=True)
class Provider:
    """One accelerator family. ``device_unit`` is the display word for a
    schedulable device ('chip' for TPU, 'device' for Intel GPU)."""

    name: str
    display_name: str
    device_unit: str
    is_accel_node: Callable[[Any], bool]
    is_accel_pod: Callable[[Any], bool]
    is_plugin_pod: Callable[[Any], bool]
    node_device_capacity: Callable[[Any], int]
    node_device_allocatable: Callable[[Any], int]
    pod_device_request: Callable[[Any], int]
    #: Fast-path pod detection: a pure predicate over the pod's merged
    #: resource-key set (objects.pod_resource_keys). classify_fleet
    #: computes the set ONCE per pod and asks each provider's predicate,
    #: instead of every provider re-walking the container list — the
    #: sync path's hottest loop at fleet scale. Must decide exactly what
    #: ``is_accel_pod`` decides (pinned by tests); None falls back to
    #: ``is_accel_pod``.
    pod_resource_test: Callable[[set[str]], bool] | None = None


TPU_PROVIDER = Provider(
    name="tpu",
    display_name="Cloud TPU",
    device_unit="chip",
    is_accel_node=tpu.is_tpu_node,
    is_accel_pod=tpu.is_tpu_requesting_pod,
    is_plugin_pod=tpu.is_tpu_plugin_pod,
    node_device_capacity=tpu.get_node_chip_capacity,
    node_device_allocatable=tpu.get_node_chip_allocatable,
    pod_device_request=tpu.get_pod_chip_request,
    pod_resource_test=lambda keys: tpu.TPU_RESOURCE in keys,
)

INTEL_PROVIDER = Provider(
    name="intel",
    display_name="Intel GPU",
    device_unit="device",
    is_accel_node=intel.is_intel_gpu_node,
    is_accel_pod=intel.is_gpu_requesting_pod,
    is_plugin_pod=intel.is_intel_plugin_pod,
    node_device_capacity=intel.get_node_gpu_count,
    node_device_allocatable=intel.get_node_gpu_allocatable,
    pod_device_request=intel.get_pod_device_request,
    pod_resource_test=lambda keys: any(
        k.startswith(intel.INTEL_GPU_RESOURCE_PREFIX) for k in keys
    ),
)

#: Registration order = sidebar/priority order. TPU first by design.
PROVIDERS: tuple[Provider, ...] = (TPU_PROVIDER, INTEL_PROVIDER)


@dataclass
class FleetView:
    """One provider's slice of a cluster snapshot."""

    provider: Provider
    nodes: list[Any] = field(default_factory=list)
    pods: list[Any] = field(default_factory=list)
    plugin_pods: list[Any] = field(default_factory=list)
    #: Snapshot generation this view was built from — stamped by the
    #: data context's ``_build_snapshot`` (monotone per context, bumped
    #: only when a sync actually changed state, so a clean tick keeps
    #: the number). It is the device-cache key
    #: (``runtime.device_cache``): same version ⇒ identical nodes/pods ⇒
    #: the device-resident columns may be reused. ``None`` (raw
    #: ``classify_fleet`` views: CLI one-shots, tests, benches) opts out
    #: of caching entirely.
    version: int | None = None

    @property
    def plugin_installed(self) -> bool:
        """Plugin presence = daemon pods seen OR devices advertised. The
        TPU side has no operator CRD, so — per the reference's own
        CRD-absent fallback (ADR-003) — allocatable devices are accepted
        as installation evidence."""
        if self.plugin_pods:
            return True
        return any(self.provider.node_device_allocatable(n) > 0 for n in self.nodes)

    def allocation_summary(self) -> Mapping[str, int]:
        return objects.allocation_summary(
            self.nodes,
            self.pods,
            self.provider.node_device_capacity,
            self.provider.node_device_allocatable,
            self.provider.pod_device_request,
        )


def classify_fleet(
    nodes: Iterable[Any],
    pods: Iterable[Any],
    providers: tuple[Provider, ...] = PROVIDERS,
) -> dict[str, FleetView]:
    """Partition a cluster snapshot into per-provider views in one pass
    over nodes and one over pods (a node or pod can belong to several
    providers only in pathological fixtures; each provider applies its own
    guard independently, so nothing is double-hidden)."""
    views = {p.name: FleetView(provider=p) for p in providers}
    for n in nodes:
        for p in providers:
            if p.is_accel_node(n):
                views[p.name].nodes.append(n)
    for pod in pods:
        # One container walk per pod, shared by every provider's
        # resource predicate (see Provider.pod_resource_test).
        resource_keys = objects.pod_resource_keys(pod)
        for p in providers:
            if (
                p.pod_resource_test(resource_keys)
                if p.pod_resource_test is not None
                else p.is_accel_pod(pod)
            ):
                views[p.name].pods.append(pod)
            if p.is_plugin_pod(pod):
                views[p.name].plugin_pods.append(pod)
    return views
