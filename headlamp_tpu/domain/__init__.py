"""Pure domain layer: TPU + Intel GPU providers over plain k8s dicts."""

from . import accelerator, constants, intel, objects, tpu  # noqa: F401
