"""Intel GPU provider — the second accelerator provider.

The BASELINE mixed-cluster config requires Intel Arc dGPU nodes and TPU
nodes to coexist and degrade independently behind one abstraction. This
module re-implements the *semantics* of the reference's Intel detection
(`/root/reference/src/api/k8s.ts:17-31,125-152,250-301`) in the framework's
dict-based style; it is intentionally thinner than the TPU provider — TPU
is first-class here, Intel is the compatibility provider.
"""

from __future__ import annotations

from typing import Any, Iterable

from . import objects as obj

INTEL_GPU_RESOURCE_PREFIX = "gpu.intel.com/"
INTEL_GPU_I915_RESOURCE = "gpu.intel.com/i915"
INTEL_GPU_XE_RESOURCE = "gpu.intel.com/xe"

INTEL_GPU_NODE_LABEL = "intel.feature.node.kubernetes.io/gpu"
INTEL_DISCRETE_GPU_ROLE = "node-role.kubernetes.io/gpu"
INTEL_INTEGRATED_GPU_ROLE = "node-role.kubernetes.io/igpu"

INTEL_PLUGIN_POD_LABELS = (
    ("app", "intel-gpu-plugin"),
    ("app.kubernetes.io/name", "intel-gpu-plugin"),
    ("component", "intel-gpu-plugin"),
)

#: Device-counting resources. Shared/monitoring resources (millicores,
#: memory.max, tiles) are capacity metadata, not devices.
_DEVICE_RESOURCES = (INTEL_GPU_I915_RESOURCE, INTEL_GPU_XE_RESOURCE)


def is_intel_gpu_node(node: Any) -> bool:
    """NFD-label OR gpu.intel.com/* capacity (k8s.ts:125-152)."""
    labels = obj.labels(node)
    if "true" in (
        labels.get(INTEL_GPU_NODE_LABEL),
        labels.get(INTEL_DISCRETE_GPU_ROLE),
        labels.get(INTEL_INTEGRATED_GPU_ROLE),
    ):
        return True
    return any(k.startswith(INTEL_GPU_RESOURCE_PREFIX) for k in obj.node_capacity(node))


def filter_intel_gpu_nodes(items: Iterable[Any]) -> list[Any]:
    return [n for n in items if is_intel_gpu_node(n)]


def get_node_gpu_count(node: Any) -> int:
    """i915 + xe capacity sum (k8s.ts:171-180)."""
    capacity = obj.node_capacity(node)
    return sum(obj.parse_int(capacity.get(r)) for r in _DEVICE_RESOURCES)


def get_node_gpu_allocatable(node: Any) -> int:
    allocatable = obj.node_allocatable(node)
    return sum(obj.parse_int(allocatable.get(r)) for r in _DEVICE_RESOURCES)


def get_node_gpu_type(node: Any) -> str:
    """'discrete' | 'integrated' | 'unknown' (k8s.ts:185-192)."""
    labels = obj.labels(node)
    if labels.get(INTEL_DISCRETE_GPU_ROLE) == "true":
        return "discrete"
    if labels.get(INTEL_INTEGRATED_GPU_ROLE) == "true":
        return "integrated"
    return "unknown"


def is_gpu_requesting_pod(pod: Any) -> bool:
    """Any container with a gpu.intel.com/* request or limit
    (k8s.ts:250-264)."""
    for c in obj.pod_containers(pod):
        merged = {**obj.container_requests(c), **obj.container_limits(c)}
        if any(k.startswith(INTEL_GPU_RESOURCE_PREFIX) for k in merged):
            return True
    return False


def filter_gpu_requesting_pods(items: Iterable[Any]) -> list[Any]:
    return [p for p in items if is_gpu_requesting_pod(p)]


def get_container_gpu_resources(container: Any) -> dict[str, tuple[int, int]]:
    """Per-container ``{resource: (request, limit)}`` over the merged
    requests∪limits key set, gpu.intel.com/* only — the single
    definition behind the pods-page container list and the pod
    detail-section rows (the reference merges the same way,
    `PodsPage.tsx:49-88`, `PodDetailSection.tsx:57-83`)."""
    requests = obj.container_requests(container)
    limits = obj.container_limits(container)
    return {
        resource: (
            obj.parse_int(requests.get(resource)),
            obj.parse_int(limits.get(resource)),
        )
        for resource in sorted({*requests, *limits})
        if resource.startswith(INTEL_GPU_RESOURCE_PREFIX)
    }


def get_pod_gpu_requests(pod: Any) -> dict[str, int]:
    """Per-resource effective requests: max(sum over main containers,
    max over init containers) — init containers run before the main ones
    and overlap rather than add (the reference sums both, k8s.ts:289-301,
    which overcounts)."""
    main: dict[str, int] = {}
    for c in obj.pod_containers(pod, include_init=False):
        for key, value in obj.container_requests(c).items():
            if key.startswith(INTEL_GPU_RESOURCE_PREFIX):
                main[key] = main.get(key, 0) + obj.parse_int(value)
    init: dict[str, int] = {}
    for c in obj.pod_init_containers(pod):
        for key, value in obj.container_requests(c).items():
            if key.startswith(INTEL_GPU_RESOURCE_PREFIX):
                init[key] = max(init.get(key, 0), obj.parse_int(value))
    return {k: max(main.get(k, 0), init.get(k, 0)) for k in {*main, *init}}


def get_pod_device_request(pod: Any) -> int:
    """Device-count request (i915 + xe only), for allocation math."""
    totals = get_pod_gpu_requests(pod)
    return sum(totals.get(r, 0) for r in _DEVICE_RESOURCES)


def is_intel_plugin_pod(pod: Any) -> bool:
    labels = obj.labels(pod)
    if not labels:
        return False
    return any(labels.get(k) == v for k, v in INTEL_PLUGIN_POD_LABELS)


# ---------------------------------------------------------------------------
# GpuDevicePlugin CRD status (reference: k8s.ts:56-80,370-386)
# ---------------------------------------------------------------------------

def plugin_status_to_status(plugin: Any) -> str:
    """'success' | 'warning' | 'error' from the CRD's rollout counters —
    the reference's state machine (k8s.ts:370-379): no desired nodes ⇒
    warning; all ready ⇒ success; else error."""
    s = obj.status(plugin)
    desired = obj.parse_int(s.get("desiredNumberScheduled"))
    ready = obj.parse_int(s.get("numberReady"))
    if desired == 0:
        return "warning"
    if ready == desired:
        return "success"
    return "error"


def plugin_status_text(plugin: Any) -> str:
    """Human rollout text (k8s.ts:381-386)."""
    s = obj.status(plugin)
    desired = obj.parse_int(s.get("desiredNumberScheduled"))
    ready = obj.parse_int(s.get("numberReady"))
    if desired == 0:
        return "No nodes scheduled"
    return f"{ready}/{desired} ready"


def format_gpu_resource_name(resource_key: str) -> str:
    """'gpu.intel.com/i915' -> 'GPU (i915)' (k8s.ts:354-364)."""
    if not resource_key.startswith(INTEL_GPU_RESOURCE_PREFIX):
        return resource_key
    suffix = resource_key[len(INTEL_GPU_RESOURCE_PREFIX):]
    pretty = {
        "i915": "GPU (i915)",
        "xe": "GPU (xe)",
        "millicores": "GPU millicores",
        "memory.max": "GPU memory",
        "tiles": "GPU tiles",
    }
    return pretty.get(suffix, f"GPU ({suffix})")


def format_gpu_type(gpu_type: str) -> str:
    """(k8s.ts:194-199)."""
    return {
        "discrete": "Discrete GPU",
        "integrated": "Integrated GPU",
    }.get(gpu_type, "Intel GPU")


def filter_intel_plugin_pods(items: Iterable[Any]) -> list[Any]:
    return [p for p in items if is_intel_plugin_pod(p)]
