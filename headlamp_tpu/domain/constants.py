"""GKE Cloud TPU constants — the TPU-side analogue of the reference's Intel
constant block (`/root/reference/src/api/k8s.ts:13-31`).

Everything the framework knows about a cluster flows from these names:
extended-resource keys on node capacity/allocatable and pod requests, and
node labels stamped by GKE when a TPU node pool is created.
"""

# ---------------------------------------------------------------------------
# Extended resource
# ---------------------------------------------------------------------------

#: Kubernetes extended resource advertised by the GKE TPU device plugin.
#: Unlike Intel's gpu.intel.com/* family this is a single resource name,
#: so detection matches it exactly rather than by prefix.
TPU_RESOURCE = "google.com/tpu"

# ---------------------------------------------------------------------------
# GKE node labels
# ---------------------------------------------------------------------------

#: Accelerator machine family, e.g. "tpu-v5-lite-podslice", "tpu-v5p-slice",
#: "tpu-v4-podslice", "tpu-v6e-slice".
GKE_TPU_ACCELERATOR_LABEL = "cloud.google.com/gke-tpu-accelerator"

#: Physical chip topology of the slice this node belongs to, e.g. "2x4" for
#: v5e or "4x4x4" for v5p/v4.
GKE_TPU_TOPOLOGY_LABEL = "cloud.google.com/gke-tpu-topology"

#: Node pool name. All hosts of one multi-host pod slice live in one node
#: pool; we group slice membership by this label.
GKE_NODEPOOL_LABEL = "cloud.google.com/gke-nodepool"

#: Optional worker index within a multi-host slice. Not all GKE versions
#: stamp it; slice grouping falls back to deterministic name ordering.
GKE_TPU_WORKER_ID_LABEL = "cloud.google.com/gke-tpu-worker-id"

#: Federation cluster membership (ADR-026 viewport tree). Multi-cluster
#: fleets arrive through one aggregated snapshot; this label names the
#: source cluster on every node. Nodes without it (every single-cluster
#: deployment) fall into the implicit cluster "0" — the viewport tree is
#: total over any fleet, labelled or not.
HEADLAMP_CLUSTER_LABEL = "headlamp.io/cluster"

# ---------------------------------------------------------------------------
# TPU device plugin DaemonSet
# ---------------------------------------------------------------------------

#: Label values identifying TPU device-plugin daemon pods. GKE runs the
#: plugin in kube-system; third-party installs vary, so detection accepts
#: any of these label pairs (mirrors the reference's 3-variant matching,
#: `/root/reference/src/api/k8s.ts:271-282`).
TPU_PLUGIN_POD_LABELS = (
    ("k8s-app", "tpu-device-plugin"),
    ("app", "tpu-device-plugin"),
    ("app.kubernetes.io/name", "tpu-device-plugin"),
)

#: Namespace GKE deploys the device plugin into.
TPU_PLUGIN_NAMESPACE = "kube-system"

# ---------------------------------------------------------------------------
# Accelerator label value -> TPU generation
# ---------------------------------------------------------------------------

#: Known gke-tpu-accelerator label values. Order matters only for docs.
TPU_ACCELERATOR_GENERATIONS = {
    "tpu-v4-podslice": "v4",
    "tpu-v5-lite-podslice": "v5e",
    "tpu-v5-lite-device": "v5e",
    "tpu-v5p-slice": "v5p",
    "tpu-v6e-slice": "v6e",
}

#: Human-readable generation names for UI display.
TPU_GENERATION_DISPLAY = {
    "v4": "TPU v4",
    "v5e": "TPU v5e",
    "v5p": "TPU v5p",
    "v6e": "TPU v6e (Trillium)",
    "unknown": "TPU (unknown gen)",
}
