"""TPU domain model — detection, chip accounting, formatting.

Role-equivalent to the reference's pure domain layer
(`/root/reference/src/api/k8s.ts`), redesigned around GKE Cloud TPU
primitives: `google.com/tpu` extended resources and
`cloud.google.com/gke-tpu-*` node labels. Pure functions over plain dicts;
zero imports outside the package (mirrors k8s.ts:1-6's zero-dep contract).
"""

from __future__ import annotations

from typing import Any, Iterable, Mapping

from . import objects as obj
from .constants import (
    GKE_NODEPOOL_LABEL,
    GKE_TPU_ACCELERATOR_LABEL,
    GKE_TPU_TOPOLOGY_LABEL,
    GKE_TPU_WORKER_ID_LABEL,
    TPU_ACCELERATOR_GENERATIONS,
    TPU_GENERATION_DISPLAY,
    TPU_PLUGIN_POD_LABELS,
    TPU_RESOURCE,
)

# ---------------------------------------------------------------------------
# Node detection
# ---------------------------------------------------------------------------

def is_tpu_node(node: Any) -> bool:
    """A node is a TPU node if GKE stamped the accelerator label OR its
    capacity advertises `google.com/tpu` (label-OR-capacity, the same
    two-signal detection the reference uses for Intel nodes,
    k8s.ts:125-152 — either signal alone is sufficient because label
    propagation and device-plugin registration can race)."""
    labels = obj.labels(node)
    if labels.get(GKE_TPU_ACCELERATOR_LABEL):
        return True
    if obj.parse_int(obj.node_capacity(node).get(TPU_RESOURCE)) > 0:
        return True
    return False


def filter_tpu_nodes(items: Iterable[Any]) -> list[Any]:
    return [n for n in items if is_tpu_node(n)]


def get_node_chip_capacity(node: Any) -> int:
    """Chips advertised in capacity (k8s.ts:171-180 analogue; TPU has a
    single resource name, not i915+xe)."""
    return obj.parse_int(obj.node_capacity(node).get(TPU_RESOURCE))


def get_node_chip_allocatable(node: Any) -> int:
    return obj.parse_int(obj.node_allocatable(node).get(TPU_RESOURCE))


def get_node_accelerator(node: Any) -> str | None:
    """Raw gke-tpu-accelerator label value, e.g. 'tpu-v5-lite-podslice'."""
    val = obj.labels(node).get(GKE_TPU_ACCELERATOR_LABEL)
    return str(val) if val else None


def get_node_topology(node: Any) -> str | None:
    """Raw gke-tpu-topology label value, e.g. '2x4' or '4x4x4'."""
    val = obj.labels(node).get(GKE_TPU_TOPOLOGY_LABEL)
    return str(val) if val else None


def get_node_pool(node: Any) -> str | None:
    val = obj.labels(node).get(GKE_NODEPOOL_LABEL)
    return str(val) if val else None


def get_node_worker_id(node: Any) -> int | None:
    """Explicit worker index within a multi-host slice, when stamped.
    Returns None (not 0) when absent so callers can fall back to
    deterministic name ordering — see topology.slices.group_slices."""
    val = obj.labels(node).get(GKE_TPU_WORKER_ID_LABEL)
    if val is None or str(val).strip() == "":
        return None
    parsed = obj.parse_int(val)
    if parsed == 0 and str(val).strip() not in ("0", "+0", "-0"):
        return None
    return parsed


def get_tpu_generation(accelerator: str | None) -> str:
    """Map an accelerator label value to a generation ('v4','v5e','v5p',
    'v6e','unknown'). Unknown future values degrade gracefully rather than
    failing detection — the TPU analogue of the reference's
    discrete/integrated/unknown trichotomy (k8s.ts:183-192)."""
    if not accelerator:
        return "unknown"
    gen = TPU_ACCELERATOR_GENERATIONS.get(accelerator)
    if gen:
        return gen
    # Heuristic for future label values: "tpu-v7x-..." -> "v7x"
    if accelerator.startswith("tpu-v"):
        tail = accelerator[len("tpu-"):]
        gen_guess = tail.split("-", 1)[0]
        if len(gen_guess) <= 4:
            return gen_guess
    return "unknown"


def get_node_generation(node: Any) -> str:
    return get_tpu_generation(get_node_accelerator(node))


def is_multi_host_node(node: Any) -> bool:
    """True when the node's slice spans multiple hosts (topology chip count
    exceeds the chips attached to this host). Needs only node-local data."""
    topology = get_node_topology(node)
    if not topology:
        return False
    from ..topology.slices import parse_topology, topology_chip_count

    dims = parse_topology(topology)
    if not dims:
        return False
    chips_here = get_node_chip_capacity(node)
    return chips_here > 0 and topology_chip_count(dims) > chips_here


# ---------------------------------------------------------------------------
# Pod detection & chip accounting
# ---------------------------------------------------------------------------

def is_tpu_requesting_pod(pod: Any) -> bool:
    """Any container (incl. init) requesting or limited by google.com/tpu
    (requests-OR-limits over the container union, k8s.ts:250-264)."""
    for c in obj.pod_containers(pod):
        if TPU_RESOURCE in obj.container_requests(c) or TPU_RESOURCE in obj.container_limits(c):
            return True
    return False


def filter_tpu_requesting_pods(items: Iterable[Any]) -> list[Any]:
    return [p for p in items if is_tpu_requesting_pod(p)]


def get_pod_chip_request(pod: Any) -> int:
    """Effective chips the pod occupies: Kubernetes reserves
    max(max(initContainers), sum(containers)) — init containers run
    sequentially before the main ones, so their requests overlap rather
    than add (the reference sums both, k8s.ts:289-301; that overcounts).
    For extended resources requests==limits is API-server-enforced, so
    requests (falling back to limits) are exact per container."""

    def chip_req(c: Mapping[str, Any]) -> int:
        req = obj.container_requests(c).get(TPU_RESOURCE)
        if req is None:
            req = obj.container_limits(c).get(TPU_RESOURCE)
        return obj.parse_int(req)

    main_sum = sum(chip_req(c) for c in obj.pod_containers(pod, include_init=False))
    init_max = max((chip_req(c) for c in obj.pod_init_containers(pod)), default=0)
    return max(main_sum, init_max)


def is_tpu_plugin_pod(pod: Any) -> bool:
    """TPU device-plugin daemon pod, by any accepted label variant
    (3-variant matching mirrors k8s.ts:271-282)."""
    labels = obj.labels(pod)
    if not labels:
        return False
    return any(labels.get(k) == v for k, v in TPU_PLUGIN_POD_LABELS)


def filter_tpu_plugin_pods(items: Iterable[Any]) -> list[Any]:
    return [p for p in items if is_tpu_plugin_pod(p)]


# ---------------------------------------------------------------------------
# DaemonSet status (TPU has no operator CRD — ADR-003 analogue)
# ---------------------------------------------------------------------------

def daemonset_status_to_status(ds: Any) -> str:
    """'success' | 'warning' | 'error' from DaemonSet rollout counters —
    the reference applies the same state machine to its CRD status
    (k8s.ts:370-379); with no TPU CRD we read the DaemonSet directly."""
    s = obj.status(ds)
    desired = obj.parse_int(s.get("desiredNumberScheduled"))
    ready = obj.parse_int(s.get("numberReady"))
    unavailable = obj.parse_int(s.get("numberUnavailable"))
    if desired == 0:
        return "warning"
    if unavailable > 0:
        return "warning"
    if ready == desired:
        return "success"
    return "error"


def daemonset_status_text(ds: Any) -> str:
    s = obj.status(ds)
    desired = obj.parse_int(s.get("desiredNumberScheduled"))
    ready = obj.parse_int(s.get("numberReady"))
    if desired == 0:
        return "No nodes scheduled"
    return f"{ready}/{desired} ready"


# ---------------------------------------------------------------------------
# Formatting
# ---------------------------------------------------------------------------

def format_generation(generation: str) -> str:
    known = TPU_GENERATION_DISPLAY.get(generation)
    if known:
        return known
    # Future generations inferred by get_tpu_generation still display
    # usefully ("TPU v7x") instead of collapsing to unknown.
    if generation and generation != "unknown":
        return f"TPU {generation}"
    return TPU_GENERATION_DISPLAY["unknown"]


def format_accelerator(accelerator: str | None) -> str:
    """Display name for an accelerator label value:
    'tpu-v5-lite-podslice' -> 'TPU v5e'."""
    return format_generation(get_tpu_generation(accelerator))


def format_chip_count(count: int) -> str:
    return f"{count} chip" if count == 1 else f"{count} chips"


def format_tpu_resource_name(resource_key: str) -> str:
    """Display name for the resource key (k8s.ts:354-364 analogue)."""
    if resource_key == TPU_RESOURCE:
        return "TPU chips"
    return resource_key


# ---------------------------------------------------------------------------
# Fleet summaries (pure aggregation used by pages and analytics)
# ---------------------------------------------------------------------------

def summarize_allocation(nodes: Iterable[Any], pods: Iterable[Any]) -> Mapping[str, int]:
    """TPU-typed allocation summary (shared math in objects.allocation_summary)."""
    return obj.allocation_summary(
        nodes, pods, get_node_chip_capacity, get_node_chip_allocatable, get_pod_chip_request
    )


#: Provider-neutral phase histogram — lives in objects; re-exported here
#: for the established TPU-page call sites.
count_pod_phases = obj.count_pod_phases
