"""ICI mesh geometry: chip coordinates, host blocks, inter-host links,
and a 2D projection the TopologyPage can render directly.

Pure integer geometry — no I/O, no floats beyond pixel positions — so the
TS mirror (`plugin/src/api/topology.ts`) can reproduce it exactly and the
shared-fixture tests can diff the two (tests/test_ts_parity.py).

Physical model (public TPU system architecture):
- A slice's chips form an N-D grid given by the topology label
  (2D for v5e/v6e, 3D for v4/v5p).
- Each host (VM) owns a contiguous block of chips: (2,2,1) on 3D
  generations, (2,2) on 2D multi-host pools, the whole grid on
  single-host pools.
- ICI links connect grid neighbours along each axis; 3D generations form
  a torus (wrap links) along axes of size >= 4.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator

from .slices import SliceInfo

# ---------------------------------------------------------------------------
# Host blocks
# ---------------------------------------------------------------------------

def host_block(dims: tuple[int, ...], chips_per_host: int) -> tuple[int, ...]:
    """Shape of the chip block owned by one host.

    Factor ``chips_per_host`` over the leading axes as evenly as possible
    (4 chips -> (2,2) or (2,2,1) when divisible; degenerate topologies fall
    back to filling the first axis)."""
    if not dims:
        return ()
    if chips_per_host <= 1:
        return tuple(1 for _ in dims)
    block = [1] * len(dims)
    remaining = chips_per_host
    # Prefer square-ish blocks: repeatedly halve over axes that divide.
    axis = 0
    guard = 0
    while remaining > 1 and guard < 64:
        guard += 1
        placed = False
        for i in range(len(dims)):
            a = (axis + i) % len(dims)
            if remaining % 2 == 0 and dims[a] % (block[a] * 2) == 0:
                block[a] *= 2
                remaining //= 2
                axis = (a + 1) % len(dims)
                placed = True
                break
        if not placed:
            # Odd or non-dividing remainder: stack what's left on the first
            # axis that can absorb it; else give the host the whole grid.
            for a in range(len(dims)):
                if dims[a] % (block[a] * remaining) == 0:
                    block[a] *= remaining
                    remaining = 1
                    placed = True
                    break
            if not placed:
                return dims
    return tuple(block)


def _grid_iter(dims: tuple[int, ...]) -> Iterator[tuple[int, ...]]:
    """Row-major iteration over all coordinates (first axis slowest)."""
    if not dims:
        return
    coord = [0] * len(dims)
    total = 1
    for d in dims:
        total *= d
    for _ in range(total):
        yield tuple(coord)
        for a in range(len(dims) - 1, -1, -1):
            coord[a] += 1
            if coord[a] < dims[a]:
                break
            coord[a] = 0


def chip_worker(coord: tuple[int, ...], block: tuple[int, ...], host_grid: tuple[int, ...]) -> int:
    """Worker (host) index owning a chip coordinate: row-major index of the
    host-block coordinate."""
    idx = 0
    for a in range(len(coord)):
        idx = idx * host_grid[a] + (coord[a] // block[a] if block[a] else 0)
    return idx


# ---------------------------------------------------------------------------
# Mesh layout
# ---------------------------------------------------------------------------

@dataclass
class MeshCell:
    chip_index: int
    coord: tuple[int, ...]
    worker_id: int
    #: 2D projection for rendering (grid units, not pixels).
    px: int
    py: int


@dataclass
class MeshLink:
    a: int  # chip_index
    b: int  # chip_index
    axis: int
    wrap: bool


@dataclass
class MeshLayout:
    dims: tuple[int, ...]
    host_grid: tuple[int, ...]
    block: tuple[int, ...]
    cells: list[MeshCell] = field(default_factory=list)
    links: list[MeshLink] = field(default_factory=list)
    width: int = 0
    height: int = 0


#: Generations whose inter-host ICI forms a torus (wrap links) on axes of
#: size >= 4. 2D generations (v5e/v6e) are plain meshes.
_TORUS_GENERATIONS = ("v4", "v5p")

#: Horizontal gap (in grid units) between z-layers in the 3D projection.
_LAYER_GAP = 1


def build_mesh_layout(sl: SliceInfo) -> MeshLayout:
    """Geometry for one slice. Unknown topology -> one row of hosts with
    no links (the honest fallback; the page labels it 'topology unknown')."""
    dims = sl.dims
    if not dims:
        cells = [
            MeshCell(chip_index=i, coord=(i,), worker_id=w.worker_id, px=i, py=0)
            for i, w in enumerate(sl.workers)
        ]
        return MeshLayout(
            dims=(),
            host_grid=(len(cells),) if cells else (0,),
            block=(1,),
            cells=cells,
            links=[],
            width=max(len(cells), 1),
            height=1,
        )

    cph = sl.chips_per_host
    block = host_block(dims, cph)
    host_grid = tuple(d // b if b else 1 for d, b in zip(dims, block))

    coords = list(_grid_iter(dims))
    index_of = {c: i for i, c in enumerate(coords)}

    cells: list[MeshCell] = []
    for i, c in enumerate(coords):
        worker = chip_worker(c, block, host_grid)
        px, py = _project(c, dims)
        cells.append(MeshCell(chip_index=i, coord=c, worker_id=worker, px=px, py=py))

    torus = sl.generation in _TORUS_GENERATIONS
    links: list[MeshLink] = []
    for i, c in enumerate(coords):
        for axis in range(len(dims)):
            size = dims[axis]
            if size < 2:
                continue
            nxt = list(c)
            nxt[axis] += 1
            if nxt[axis] < size:
                links.append(MeshLink(a=i, b=index_of[tuple(nxt)], axis=axis, wrap=False))
            elif torus and size >= 4:
                nxt[axis] = 0
                links.append(MeshLink(a=i, b=index_of[tuple(nxt)], axis=axis, wrap=True))

    width = max((cell.px for cell in cells), default=0) + 1
    height = max((cell.py for cell in cells), default=0) + 1
    return MeshLayout(
        dims=dims, host_grid=host_grid, block=block,
        cells=cells, links=links, width=width, height=height,
    )


def _project(coord: tuple[int, ...], dims: tuple[int, ...]) -> tuple[int, int]:
    """2D projection: 1D -> a row; 2D -> identity; 3D+ -> layers side by
    side, each layer an x-y grid. Axes beyond the second collapse into a
    single row-major layer index so even a future 4D topology keeps
    every chip at a distinct position."""
    if len(coord) == 1:
        return coord[0], 0
    if len(coord) == 2:
        return coord[0], coord[1]
    layer = 0
    for a in range(2, len(coord)):
        layer = layer * dims[a] + coord[a]
    return coord[0] + layer * (dims[0] + _LAYER_GAP), coord[1]
