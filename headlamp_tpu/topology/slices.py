"""Pod-slice modeling: from GKE node labels to slice membership.

The hardest structural difference from the Intel reference: one logical
TPU "device" (a pod slice) can span many Kubernetes nodes (hosts). GKE
exposes only per-node labels — accelerator, topology string, node pool —
so slice identity, expected host counts, and worker ordering must all be
*derived*. This module does that derivation purely (no I/O), feeding both
the TopologyPage and the health model (SURVEY.md §7 hard part (a)).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Iterable, Mapping

from ..domain import objects as obj
from ..domain import tpu

# ---------------------------------------------------------------------------
# Topology strings
# ---------------------------------------------------------------------------

_TOPOLOGY_RE = re.compile(r"^\d+(x\d+)*$")


def parse_topology(topology: str | None) -> tuple[int, ...]:
    """'4x4x4' -> (4, 4, 4). Invalid/absent input -> () — callers treat an
    empty tuple as "unknown topology" and degrade, never raise."""
    if not topology or not _TOPOLOGY_RE.match(topology.strip()):
        return ()
    dims = tuple(int(d) for d in topology.strip().split("x"))
    if any(d <= 0 for d in dims):
        return ()
    return dims


def topology_chip_count(dims: tuple[int, ...]) -> int:
    count = 1
    for d in dims:
        count *= d
    return count if dims else 0


#: Default chips attached to one host (VM) per generation, used only when
#: no node in a slice advertises capacity. v4/v5p hosts always carry 4
#: chips; v5e/v6e multi-host pools carry 4 (single-host pools carry the
#: whole topology and are detected from capacity instead).
DEFAULT_CHIPS_PER_HOST = {"v4": 4, "v5p": 4, "v5e": 4, "v6e": 4, "unknown": 4}


def infer_chips_per_host(generation: str, dims: tuple[int, ...], observed: int = 0) -> int:
    """Chips per host for a slice. The observed per-node capacity wins —
    it disambiguates cases like v5e '2x4', which GKE offers both as one
    8-chip host and as two 4-chip hosts depending on machine type (the
    label alone cannot tell them apart)."""
    total = topology_chip_count(dims)
    if observed > 0:
        return min(observed, total) if total else observed
    default = DEFAULT_CHIPS_PER_HOST.get(generation, 4)
    if total and total < default:
        return total
    # 2D generations pack small topologies onto one host.
    if total and len(dims) == 2 and generation in ("v5e", "v6e") and total <= 8:
        return total
    return default


def expected_host_count(generation: str, dims: tuple[int, ...], observed_chips: int = 0) -> int:
    total = topology_chip_count(dims)
    if total == 0:
        return 1
    cph = infer_chips_per_host(generation, dims, observed_chips)
    return max(1, -(-total // cph))  # ceil


# ---------------------------------------------------------------------------
# Slice grouping
# ---------------------------------------------------------------------------

_NATURAL_SPLIT = re.compile(r"(\d+)")


def _natural_key(name: str) -> tuple:
    """'pool-w10' sorts after 'pool-w2' — worker ordering must be numeric,
    not lexicographic, or 16-host slices interleave wrongly."""
    return tuple(int(p) if p.isdigit() else p for p in _NATURAL_SPLIT.split(name))


@dataclass
class SliceWorker:
    node: Any
    worker_id: int
    ready: bool
    chip_capacity: int

    @property
    def node_name(self) -> str:
        return obj.name(self.node)


@dataclass
class SliceInfo:
    """One pod slice: the unit the TopologyPage renders and the health
    model reasons about."""

    slice_id: str
    node_pool: str
    accelerator: str | None
    generation: str
    topology: str | None
    dims: tuple[int, ...]
    workers: list[SliceWorker] = field(default_factory=list)

    @property
    def total_chips(self) -> int:
        if self.dims:
            return topology_chip_count(self.dims)
        return sum(w.chip_capacity for w in self.workers)

    @property
    def chips_per_host(self) -> int:
        observed = max((w.chip_capacity for w in self.workers), default=0)
        return infer_chips_per_host(self.generation, self.dims, observed)

    @property
    def expected_hosts(self) -> int:
        observed = max((w.chip_capacity for w in self.workers), default=0)
        if not self.dims:
            return max(1, len(self.workers))
        return expected_host_count(self.generation, self.dims, observed)

    @property
    def actual_hosts(self) -> int:
        return len(self.workers)

    @property
    def is_multi_host(self) -> bool:
        return self.expected_hosts > 1

    @property
    def complete(self) -> bool:
        """Every expected worker slot is filled. Defined via
        missing_worker_ids so explicit out-of-range ids (e.g. workers
        {0,1,2,4} of an expected 4) count as incomplete instead of
        reporting a healthy slice that also lists a missing worker."""
        return not self.missing_worker_ids

    @property
    def ready_hosts(self) -> int:
        return sum(1 for w in self.workers if w.ready)

    @property
    def missing_worker_ids(self) -> list[int]:
        present = {w.worker_id for w in self.workers}
        return [i for i in range(self.expected_hosts) if i not in present]

    @property
    def health(self) -> str:
        """'success' when all expected hosts are present and ready;
        'warning' when present but not all ready; 'error' when hosts are
        missing — an incomplete multi-host slice cannot schedule any
        slice-wide workload, so it outranks mere unreadiness."""
        if not self.complete:
            return "error"
        if self.ready_hosts < self.actual_hosts:
            return "warning"
        return "success"


def group_slices(nodes: Iterable[Any]) -> list[SliceInfo]:
    """Group TPU nodes into slices.

    Slice identity on GKE: one *multi-host* node pool hosts exactly one
    pod slice, so (node pool) is the slice key — but only when the pool's
    topology actually spans hosts. A single-host pool (topology fits on
    one node, e.g. an autoscaled v5e-4 pool) holds one independent slice
    *per node*; merging those would undercount chips and misreport
    health. Nodes without a pool label each form a degenerate
    single-node slice. Worker order: explicit gke-tpu-worker-id labels
    when every node in the slice carries a distinct one, else natural
    name order (stable across refreshes).
    """
    by_pool: dict[str, list[Any]] = {}
    singletons: list[Any] = []
    for n in nodes:
        if not tpu.is_tpu_node(n):
            continue
        pool = tpu.get_node_pool(n)
        if pool:
            by_pool.setdefault(pool, []).append(n)
        else:
            singletons.append(n)

    slices: list[SliceInfo] = []
    for pool, members in sorted(by_pool.items()):
        if _pool_is_multi_host(members):
            slices.append(_build_slice(pool, pool, members))
        else:
            for n in sorted(members, key=lambda n: _natural_key(obj.name(n))):
                node_name = obj.name(n) or "node"
                slices.append(_build_slice(f"{pool}/{node_name}", pool, [n]))
    for n in singletons:
        node_name = obj.name(n) or "node"
        slices.append(_build_slice(f"node/{node_name}", f"(no pool) {node_name}", [n]))
    return slices


def _labeled_member(members: list[Any]) -> Any:
    """The member to read slice-level labels from: prefer one whose
    topology label has propagated — is_tpu_node tolerates the label/
    device-plugin registration race, so the first node in input order may
    know only its capacity while its siblings carry the full labels."""
    for n in members:
        if tpu.get_node_topology(n):
            return n
    return members[0]


def _pool_is_multi_host(members: list[Any]) -> bool:
    """A pool's topology spans hosts when the slice needs more than one
    node: topology chip count exceeds the chips observed on a member."""
    labeled = _labeled_member(members)
    dims = parse_topology(tpu.get_node_topology(labeled))
    if not dims:
        return False
    generation = tpu.get_tpu_generation(tpu.get_node_accelerator(labeled))
    observed = max((tpu.get_node_chip_capacity(n) for n in members), default=0)
    return expected_host_count(generation, dims, observed) > 1


def _build_slice(slice_id: str, pool_name: str, members: list[Any]) -> SliceInfo:
    first = _labeled_member(members)
    accelerator = tpu.get_node_accelerator(first)
    topology = tpu.get_node_topology(first)
    generation = tpu.get_tpu_generation(accelerator)
    dims = parse_topology(topology)

    explicit = [tpu.get_node_worker_id(n) for n in members]
    ids_ok = all(i is not None for i in explicit) and len(set(explicit)) == len(explicit)

    if ids_ok:
        ordered = sorted(zip(explicit, members), key=lambda t: t[0])  # type: ignore[arg-type]
        workers = [
            SliceWorker(
                node=n,
                worker_id=int(wid),  # type: ignore[arg-type]
                ready=obj.is_node_ready(n),
                chip_capacity=tpu.get_node_chip_capacity(n),
            )
            for wid, n in ordered
        ]
    else:
        ordered_nodes = sorted(members, key=lambda n: _natural_key(obj.name(n)))
        workers = [
            SliceWorker(
                node=n,
                worker_id=i,
                ready=obj.is_node_ready(n),
                chip_capacity=tpu.get_node_chip_capacity(n),
            )
            for i, n in enumerate(ordered_nodes)
        ]

    return SliceInfo(
        slice_id=slice_id,
        node_pool=pool_name,
        accelerator=accelerator,
        generation=generation,
        topology=topology,
        dims=dims,
        workers=workers,
    )


def summarize_slices(slices: Iterable[SliceInfo]) -> Mapping[str, int]:
    """Fleet-level slice counters for the Overview/Topology headers."""
    total = healthy = degraded = incomplete = multi_host = chips = 0
    for s in slices:
        total += 1
        chips += s.total_chips
        if s.is_multi_host:
            multi_host += 1
        if s.health == "success":
            healthy += 1
        elif s.health == "warning":
            degraded += 1
        else:
            incomplete += 1
    return {
        "total": total,
        "healthy": healthy,
        "degraded": degraded,
        "incomplete": incomplete,
        "multi_host": multi_host,
        "total_chips": chips,
    }
