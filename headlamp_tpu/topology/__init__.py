"""ICI pod-slice topology: slice grouping and mesh geometry."""

from .mesh import MeshCell, MeshLayout, MeshLink, build_mesh_layout, host_block  # noqa: F401
from .slices import (  # noqa: F401
    SliceInfo,
    SliceWorker,
    expected_host_count,
    group_slices,
    infer_chips_per_host,
    parse_topology,
    summarize_slices,
    topology_chip_count,
)
