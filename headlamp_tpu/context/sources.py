"""Per-provider data-source descriptors.

The reference hard-codes its imperative fetch targets inside the context:
the CRD list URL (`IntelGpuDataContext.tsx:125`) and a 3-URL fallback
chain for plugin daemon pods (`:142-151` — two label selectors, then the
whole install namespace filtered client-side). This module lifts those
targets into data so each provider declares *where* its plugin state
lives and the context stays provider-agnostic.

Terminology: a provider's **workload object** is the API object that
describes the device-plugin deployment — the Intel operator's
``GpuDevicePlugin`` CRD for Intel, the device-plugin ``DaemonSet`` for
TPU (GKE ships no TPU operator CRD, so the DaemonSet *is* the
installation record; SURVEY.md §7 hard part (d)).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Mapping

from ..domain import intel, tpu
from ..domain.constants import TPU_PLUGIN_NAMESPACE

#: Reactive-track list endpoints (the ``useList`` analogues,
#: `IntelGpuDataContext.tsx:98-99` — Pod.useList({namespace: ''}) is the
#: all-namespaces list).
NODES_PATH = "/api/v1/nodes"
PODS_PATH = "/api/v1/pods"

#: Optional server-side pod filter for fleet-scale clusters: completed
#: pods keep their TPU/GPU requests in spec but hold no devices, and on
#: batch-heavy clusters they dominate the list. Pass to
#: ``AcceleratorDataContext(pod_field_selector=...)`` to drop them at
#: the apiserver instead of in the client filter.
ACTIVE_PODS_FIELD_SELECTOR = "status.phase!=Succeeded,status.phase!=Failed"


@dataclass(frozen=True)
class ProviderSource:
    """Where one provider's imperative-track state lives.

    ``plugin_pod_paths`` is a fallback chain tried sequentially with
    per-request timeouts and silent per-path failure, results merged and
    UID-deduped — exactly the reference's daemon-pod strategy
    (`IntelGpuDataContext.tsx:142-174`). ``workload_paths`` is the same
    kind of chain for the workload object; a miss on every path flips
    ``workload_available`` to False *without* surfacing an error
    (graceful degradation, ADR-003).
    """

    provider_name: str
    workload_kind: str
    workload_paths: tuple[str, ...]
    plugin_pod_paths: tuple[str, ...]
    #: Client-side filter applied to pods fetched from namespace-wide
    #: fallback paths (label-selector paths already filter server-side,
    #: but re-filtering is harmless and keeps merging uniform).
    plugin_pod_filter: Callable[[Any], bool]


TPU_SOURCE = ProviderSource(
    provider_name="tpu",
    workload_kind="DaemonSet",
    workload_paths=(
        "/apis/apps/v1/daemonsets?labelSelector=k8s-app%3Dtpu-device-plugin",
        f"/apis/apps/v1/namespaces/{TPU_PLUGIN_NAMESPACE}/daemonsets",
    ),
    plugin_pod_paths=(
        "/api/v1/pods?labelSelector=k8s-app%3Dtpu-device-plugin",
        "/api/v1/pods?labelSelector=app%3Dtpu-device-plugin",
        f"/api/v1/namespaces/{TPU_PLUGIN_NAMESPACE}/pods",
    ),
    plugin_pod_filter=tpu.is_tpu_plugin_pod,
)

INTEL_SOURCE = ProviderSource(
    provider_name="intel",
    workload_kind="GpuDevicePlugin",
    workload_paths=(
        # The operator CRD list — the reference's only workload source
        # (`IntelGpuDataContext.tsx:125`).
        "/apis/deviceplugin.intel.com/v1/gpudeviceplugins",
    ),
    plugin_pod_paths=(
        "/api/v1/pods?labelSelector=app%3Dintel-gpu-plugin",
        "/api/v1/pods?labelSelector=app.kubernetes.io%2Fname%3Dintel-gpu-plugin",
        "/api/v1/namespaces/inteldeviceplugins-system/pods",
    ),
    plugin_pod_filter=intel.is_intel_plugin_pod,
)


def default_sources() -> dict[str, ProviderSource]:
    return {s.provider_name: s for s in (TPU_SOURCE, INTEL_SOURCE)}


def workload_matches_provider(source: ProviderSource, workload: Any) -> bool:
    """Keep only workload objects that belong to the provider when a
    fallback path returned a whole namespace's worth. DaemonSets match by
    name/label mention of the plugin; CRD lists are already scoped by
    group so any kind match passes."""
    from ..domain import objects as obj

    if not isinstance(workload, Mapping):
        return False
    kind = str(workload.get("kind", ""))
    if source.workload_kind == "GpuDevicePlugin":
        return kind in ("", "GpuDevicePlugin")
    needle = f"{source.provider_name}-device-plugin"
    return needle in obj.name(workload) or needle in obj.labels(workload).values()
