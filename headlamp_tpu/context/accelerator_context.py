"""AcceleratorDataContext — single source of truth for cluster state.

The multi-provider generalization of the reference's context provider
(`/root/reference/src/api/IntelGpuDataContext.tsx:96-252`, ADR-001/002):

- **Reactive track**: node + all-namespace pod lists (the ``useList``
  analogue, `:98-99`). Fetched paginated on the first sync; with watch
  enabled (``enable_watch`` — wired by the server's background sync),
  later syncs poll a bounded ``watch=true&resourceVersion=`` delta
  stream and apply ADDED/MODIFIED/DELETED events to the object stores,
  re-listing only on 410 Gone or watch failure — the full list+watch
  protocol behind the reference's ``useList``, so steady state moves
  deltas, not the fleet. A failure leaves the previous list in place
  and records the error stream.
- **Imperative track**: per-provider workload objects (CRDs/DaemonSets)
  and plugin daemon pods via fallback chains with per-request timeouts,
  silent per-path failure, and UID dedup (`:113-190`). Workload-source
  absence degrades gracefully to ``workload_available=False`` instead of
  erroring (ADR-003 `:133-137`).
- ``refresh()`` re-runs the imperative track only, mirroring the
  reference's ``refreshKey`` effect (`:109-111,190`); ``sync()`` runs
  both tracks.

Derived per-provider views (nodes/pods filters) are computed once per
sync — the analogue of the reference's ``useMemo`` filters (`:200-208`)
— not per page render, which is what keeps the 256-node dashboard p50
inside the BASELINE budget.
"""

from __future__ import annotations

import concurrent.futures
import time
import urllib.parse
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Mapping

from ..domain import objects as obj
from ..domain.accelerator import PROVIDERS, FleetView, Provider, classify_fleet
from ..transport.api_proxy import DEFAULT_TIMEOUT_S, ApiError, Transport
from ..transport.pool import fanout, pool_of
from .sources import ProviderSource, default_sources, workload_matches_provider
from .sources import NODES_PATH, PODS_PATH


class _WatchExpired(Exception):
    """The watch cursor predates the apiserver's retained window (410
    Gone, delivered either as an HTTP status or an ERROR event) — the
    protocol's signal to resync via full re-list."""


@dataclass
class ProviderState:
    """One provider's slice of the snapshot — the per-provider
    generalization of ``IntelGpuContextValue``
    (`IntelGpuDataContext.tsx:28-52`)."""

    provider: Provider
    view: FleetView
    #: Workload objects (Intel: GpuDevicePlugin CRs; TPU: DaemonSets).
    workloads: list[Any] = field(default_factory=list)
    #: False when every workload path failed — the ``crdAvailable``
    #: analogue (`:133-137`); pages show a "not available" notice.
    workload_available: bool = True
    #: Set when every plugin-pod selector path failed for this provider.
    #: Kept per-provider (not in the global error banner) so an absent
    #: provider degrades independently; the provider's own pages may
    #: surface it.
    plugin_pods_error: str | None = None

    @property
    def nodes(self) -> list[Any]:
        return self.view.nodes

    @property
    def pods(self) -> list[Any]:
        return self.view.pods

    @property
    def plugin_pods(self) -> list[Any]:
        return self.view.plugin_pods

    @property
    def plugin_installed(self) -> bool:
        """Workloads seen OR daemon pods seen OR devices advertised
        (`:222` generalized; the device-advertised arm covers TPU's
        no-CRD world, SURVEY.md §7 hard part (d))."""
        return bool(self.workloads) or self.view.plugin_installed

    def allocation_summary(self) -> Mapping[str, int]:
        return self.view.allocation_summary()

    #: Lazily-computed dashboard aggregates (see analytics.stats).
    _stats: Mapping[str, Any] | None = None

    def fleet_stats(self) -> Mapping[str, Any]:
        """Every dashboard aggregate for this provider, computed once
        per snapshot: the XLA fused rollup on jax-capable hosts (TPU
        provider), pure-Python fallback otherwise — identical keys
        either way (``analytics/stats.py``)."""
        if self._stats is None:
            from ..analytics.stats import fleet_stats

            self._stats = fleet_stats(self.view)
        return self._stats


@dataclass
class ClusterSnapshot:
    """Immutable view handed to pages; ``None`` lists mean the track has
    never succeeded (the reference's ``loading`` definition `:214`)."""

    all_nodes: list[Any] | None
    all_pods: list[Any] | None
    providers: dict[str, ProviderState]
    errors: list[str]
    fetched_at: float
    refresh_count: int

    @property
    def loading(self) -> bool:
        return self.all_nodes is None or self.all_pods is None

    @property
    def error(self) -> str | None:
        """The page-facing aggregate: streams joined by '; '
        (`IntelGpuDataContext.tsx:216-220`)."""
        return "; ".join(self.errors) if self.errors else None

    def provider(self, name: str) -> ProviderState:
        return self.providers[name]


class AcceleratorDataContext:
    """Owns cluster state; pages read snapshots, never the transport.

    ``transport`` and ``clock`` are injected for testability (the same
    seam the vitest suite gets by mocking the Headlamp SDK module,
    `IntelGpuDataContext.test.tsx:7-15`).
    """

    #: Reactive-track page size. 500 keeps each page's JSON well under
    #: what a 2 s per-request timeout can move even on a slow apiserver;
    #: a 10k-pod fleet costs 20 requests, each individually timed out.
    PAGE_LIMIT = 500
    #: Runaway-loop backstop for a server that keeps returning continue
    #: tokens (200 pages × 500 = 100k objects — far beyond any fleet
    #: this dashboard targets).
    MAX_PAGES = 200
    #: Server-side watch window (``timeoutSeconds=``): the apiserver
    #: holds the bounded watch open this long collecting events before
    #: closing the stream. Short, because each sync is a delta *poll* —
    #: the background loop's interval provides the cadence.
    WATCH_WINDOW_S = 1.0

    def __init__(
        self,
        transport: Transport,
        *,
        providers: tuple[Provider, ...] = PROVIDERS,
        sources: Mapping[str, ProviderSource] | None = None,
        timeout_s: float = DEFAULT_TIMEOUT_S,
        clock: Callable[[], float] = time.time,
        page_limit: int | None = None,
        pod_field_selector: str | None = None,
        watch: bool = False,
    ) -> None:
        self._transport = transport
        self._providers = providers
        self._sources = dict(sources if sources is not None else default_sources())
        self._timeout_s = timeout_s
        # Wall clock on purpose (ADR-013 clock audit): it only stamps
        # snapshot.fetched_at, a displayed timestamp pages show as
        # "fetched HH:MM:SS". Elapsed-time telemetry (sync coalescing,
        # healthz staleness, cache TTLs) lives in the server app on
        # time.monotonic and must never derive from this.
        self._clock = clock
        self._page_limit = page_limit if page_limit is not None else self.PAGE_LIMIT
        #: Optional server-side pod filter (e.g. ACTIVE_PODS_FIELD_SELECTOR
        #: drops Succeeded/Failed pods) — a fleet-scale option the
        #: reference's all-namespace useList has no analogue for.
        self._pod_field_selector = pod_field_selector
        #: Incremental reactive syncs (list+watch). Off by default: a
        #: one-shot CLI render or an infrequent inline sync gains nothing
        #: from a delta protocol; the server's background loop turns it
        #: on (`DashboardApp.start_background_sync`).
        self._watch_enabled = watch

        self._all_nodes: list[Any] | None = None
        self._all_pods: list[Any] | None = None
        self._node_error: str | None = None
        self._pod_error: str | None = None
        #: Per-track incremental state: object store (key → object,
        #: insertion-ordered) and the watch cursor. An empty cursor means
        #: no successful LIST yet — watch stays disarmed until one lands.
        self._track_store: dict[str, dict[str, Any]] = {"nodes": {}, "pods": {}}
        self._track_rv: dict[str, str] = {"nodes": "", "pods": ""}
        #: Observability: how many full re-lists vs watch polls vs
        #: applied events each track has seen (surfaced by /healthz
        #: consumers and asserted by the watch tests).
        self.watch_stats: dict[str, dict[str, int]] = {
            "nodes": {"relists": 0, "watches": 0, "events": 0},
            "pods": {"relists": 0, "watches": 0, "events": 0},
        }
        self._workloads: dict[str, list[Any]] = {}
        self._workload_available: dict[str, bool] = {}
        self._fallback_plugin_pods: dict[str, list[Any]] = {}
        self._plugin_pod_errors: dict[str, str | None] = {}
        self._refresh_count = 0
        self._cached_snapshot: ClusterSnapshot | None = None
        #: Monotone snapshot generation, bumped by every _build_snapshot
        #: and stamped onto each provider FleetView (FleetView.version).
        #: Clean ticks reuse the cached snapshot and therefore the
        #: generation — which is exactly the invalidation contract the
        #: device-resident fleet cache keys on (ADR-012): unchanged
        #: fleet ⇒ same version ⇒ warm device arrays stay valid.
        self._snapshot_generation = 0
        #: Set by either track when a sync actually changed state (watch
        #: events applied, a re-list ran, imperative results differed,
        #: an error stream flipped). A CLEAN tick — quiet watch, stable
        #: chains — preserves the cached snapshot (and its computed
        #: fleet stats) instead of reclassifying the fleet: at 1024
        #: nodes that is the entire steady-state background cost.
        #: Written from the reactive worker thread too — bool stores are
        #: GIL-atomic, and it only ever transitions False→True within a
        #: sync.
        self._changed = True

    def advance_generation_floor(self, floor: int) -> None:
        """Jump the generation counter to at least ``floor`` (ADR-025
        fencing): a newly elected leader floors its context at
        ``fencing × GENERATION_STRIDE`` so every generation it publishes
        carries its leadership term in the high digits — the bus and
        replicas then reject a deposed leader's lower-band generations
        with the plain monotonicity check. Never moves backwards, so a
        re-election of the same process is harmless."""
        if floor > self._snapshot_generation:
            self._snapshot_generation = int(floor)
            # The cached snapshot's views carry pre-floor versions; the
            # next build must restamp, not reuse them.
            self._changed = True

    # ------------------------------------------------------------------
    # Track 1: reactive lists
    # ------------------------------------------------------------------

    def _list_paginated(self, path: str) -> tuple[list[Any], str]:
        """Full list via ``limit=N&continue=<token>`` chunks — the
        fleet-scale replacement for the reference's single unpaginated
        ``useList`` GET (`IntelGpuDataContext.tsx:98-99`): on a 1 000+
        node cluster one monolithic list is tens of MB and cannot finish
        inside the per-request timeout, while every 500-object page can.
        Each page request gets the full ``timeout_s``. An expired
        continue token (apiserver answers 410 Gone) or any mid-chain
        failure raises; the caller keeps the previous good list. Returns
        ``(items, resourceVersion)`` — the first page's list RV, which
        pins the snapshot the continue chain reads and is the cursor a
        subsequent watch resumes from."""
        items: list[Any] = []
        continue_token = ""
        resource_version = ""
        sep = "&" if "?" in path else "?"
        for _ in range(self.MAX_PAGES):
            url = f"{path}{sep}limit={self._page_limit}"
            if continue_token:
                url += "&continue=" + urllib.parse.quote(continue_token, safe="")
            data = self._transport.request(url, self._timeout_s)
            items.extend(obj.kube_list_items(data))
            continue_token = ""
            if isinstance(data, Mapping):
                metadata = data.get("metadata")
                if isinstance(metadata, Mapping):
                    continue_token = str(metadata.get("continue") or "")
                    if not resource_version:
                        resource_version = str(metadata.get("resourceVersion") or "")
            if not continue_token:
                return items, resource_version
        raise ApiError(path, f"list did not terminate within {self.MAX_PAGES} pages")

    def _pods_path(self) -> str:
        if self._pod_field_selector:
            return (
                PODS_PATH
                + "?fieldSelector="
                + urllib.parse.quote(self._pod_field_selector, safe="")
            )
        return PODS_PATH

    def enable_watch(self, enabled: bool = True) -> None:
        """Switch the reactive track to incremental list+watch syncs.
        Takes effect on the next ``sync()``; the first one after a cold
        start still pays a full LIST (there is no cursor yet)."""
        self._watch_enabled = enabled

    @staticmethod
    def _obj_key(o: Any) -> str:
        """Store key: UID when present (the identity Kubernetes dedups
        by), name as the fixture-friendly fallback."""
        return obj.uid(o) or obj.name(o)

    def _watch_path(self, path: str, resource_version: str) -> str:
        sep = "&" if "?" in path else "?"
        return (
            f"{path}{sep}watch=true"
            f"&resourceVersion={urllib.parse.quote(resource_version, safe='')}"
            "&allowWatchBookmarks=true"
            f"&timeoutSeconds={max(int(self.WATCH_WINDOW_S), 1)}"
        )

    def _apply_watch_events(self, track: str, events: list[Any]) -> int:
        """Apply a watch response to the track's store. Returns the
        number of object events applied. Raises :class:`_WatchExpired`
        on a 410 ERROR event and :class:`ApiError` on any other ERROR —
        both make the caller fall back to a full re-list."""
        store = self._track_store[track]
        applied = 0
        for event in events:
            if not isinstance(event, Mapping):
                continue
            etype = str(event.get("type", ""))
            payload = event.get("object")
            if etype == "ERROR":
                code = payload.get("code") if isinstance(payload, Mapping) else None
                if code == 410:
                    raise _WatchExpired()
                raise ApiError(track, f"watch ERROR event: {payload}")
            if not isinstance(payload, Mapping):
                continue
            if etype in ("ADDED", "MODIFIED"):
                store[self._obj_key(payload)] = payload
                applied += 1
            elif etype == "DELETED":
                store.pop(self._obj_key(payload), None)
                applied += 1
            # Advance the cursor from every event (bookmarks included —
            # that is their entire purpose: moving the cursor past quiet
            # stretches so it cannot expire).
            rv = obj.metadata(payload).get("resourceVersion")
            if rv:
                self._track_rv[track] = str(rv)
        return applied

    def _sync_track(self, track: str, path: str) -> str | None:
        """Sync one reactive list; returns the error string for the
        stream (or None). Incremental watch when enabled, armed (a prior
        LIST recorded a cursor), and the transport supports it; full
        paginated re-list otherwise — and as the fallback for ANY watch
        failure, 410 Gone included, so a watch-incapable or degraded
        server costs exactly the pre-watch behavior."""
        stats = self.watch_stats[track]
        watcher = getattr(self._transport, "watch", None)
        if self._watch_enabled and watcher is not None and self._track_rv[track]:
            try:
                events = watcher(
                    self._watch_path(path, self._track_rv[track]),
                    self.WATCH_WINDOW_S + self._timeout_s,
                )
                applied = self._apply_watch_events(track, events)
            except (_WatchExpired, ApiError):
                pass  # fall through to the re-list below
            else:
                stats["watches"] += 1
                stats["events"] += applied
                if applied:
                    self._changed = True
                return None
        try:
            items, resource_version = self._list_paginated(path)
        except ApiError as e:
            return f"{track}: {e}"
        self._track_store[track] = {self._obj_key(o): o for o in items}
        self._track_rv[track] = resource_version
        stats["relists"] += 1
        self._changed = True
        return None

    def _sync_reactive(self) -> None:
        # The two tracks are independent (separate stores, cursors,
        # error streams) and run concurrently: with watch enabled a
        # quiet bounded watch blocks its full server-side window, and
        # serial polls would double every tick's duration — and the
        # sync-lock hold time the server's request path can stall on.
        # One persistent worker (created on first sync, reused for the
        # context's lifetime) carries the nodes track while the calling
        # thread runs the pods track — zero per-tick thread churn.
        pool = getattr(self, "_reactive_pool", None)
        if pool is None:
            pool = self._reactive_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="hl-tpu-reactive"
            )
        try:
            nodes_future = pool.submit(self._sync_track, "nodes", NODES_PATH)
        except RuntimeError:
            # close() raced this sync and shut the pool down between the
            # getattr and the submit. A crashed tick would be worse than
            # a serial one: run both tracks inline this once; the next
            # sync recreates the pool.
            nodes_future = None
        if nodes_future is None:
            self._node_error = self._sync_track("nodes", NODES_PATH)
            self._pod_error = self._sync_track("pods", self._pods_path())
        else:
            self._pod_error = self._sync_track("pods", self._pods_path())
            self._node_error = nodes_future.result()
        if self._node_error is None:
            self._all_nodes = list(self._track_store["nodes"].values())
        if self._pod_error is None:
            self._all_pods = list(self._track_store["pods"].values())

    # ------------------------------------------------------------------
    # Track 2: imperative per-provider fetches
    # ------------------------------------------------------------------

    def _sync_imperative(self, detect_changes: bool = True) -> None:
        """Per-provider chains run concurrently: the chains are
        independent, and a blackholed provider (e.g. firewalled Intel
        namespaces on a TPU-only cluster) must cost the slowest single
        chain, not the sum of every chain's timeouts.

        Change detection: the refetched results are FINGERPRINT-compared
        to the previous tick's — (uid, resourceVersion) per object, not
        a deep dict walk; plugin daemon pods scale with the fleet, and a
        deep compare every tick would re-spend the CPU the clean-tick
        snapshot reuse exists to save. Only a real difference marks the
        sync dirty (see ``_changed``)."""
        sourced = [
            (p, self._sources[p.name])
            for p in self._providers
            if p.name in self._sources
        ]
        for p in self._providers:
            if p.name not in self._sources:
                self._workload_available[p.name] = False
        if not sourced:
            return

        # refresh() invalidates the snapshot unconditionally — skip the
        # fingerprint walks when nobody will read the verdict.
        before = self._imperative_fingerprint() if detect_changes else None

        def fetch_one(item: tuple[Provider, ProviderSource]) -> None:
            provider, source = item
            self._fetch_workloads(provider, source)
            self._fetch_plugin_pods(provider, source)

        if len(sourced) == 1:
            fetch_one(sourced[0])
        else:
            # Shared RTT-aware scheduler (ADR-014): persistent workers
            # instead of a per-tick ThreadPoolExecutor, width sized from
            # the transport pool's RTT stats when real sockets back it.
            fanout.map(fetch_one, sourced, pool=pool_of(self._transport))

        if detect_changes and self._imperative_fingerprint() != before:
            self._changed = True

    def _imperative_fingerprint(self) -> tuple:
        """Cheap identity of the imperative-track results: (uid,
        resourceVersion) per object instead of deep dict equality —
        plugin daemon pods scale with the fleet. An object whose content
        changed without a resourceVersion bump cannot come from a real
        apiserver (every write bumps it), so the fingerprint is exact
        for the transitions that matter."""

        def fp(objs: list[Any]) -> tuple:
            return tuple(
                (obj.uid(o), str(obj.metadata(o).get("resourceVersion", "")))
                for o in objs
            )

        return (
            {name: fp(objs) for name, objs in self._workloads.items()},
            dict(self._workload_available),
            {name: fp(objs) for name, objs in self._fallback_plugin_pods.items()},
            dict(self._plugin_pod_errors),
        )

    def _fetch_workloads(self, provider: Provider, source: ProviderSource) -> None:
        """Fallback chain; total failure degrades silently to
        ``workload_available=False`` (ADR-003 — a cluster without the
        Intel operator or a visible DaemonSet is healthy, not broken).
        A path that succeeds with zero matches does NOT stop the chain:
        a plugin DaemonSet labeled differently from the primary selector
        returns an empty 200 there, and only the namespace fallback with
        client-side matching can find it. Any HTTP success keeps
        ``workload_available`` True (the source exists; it may simply
        hold nothing yet)."""
        matched: list[Any] = []
        any_success = False
        for path in source.workload_paths:
            try:
                data = self._transport.request(path, self._timeout_s)
            except ApiError:
                continue
            any_success = True
            items = obj.kube_list_items(data) if obj.is_kube_list(data) else (
                [data] if isinstance(data, Mapping) else []
            )
            matched = [w for w in items if workload_matches_provider(source, w)]
            if matched:
                break
        self._workloads[provider.name] = obj.dedup_by_uid(matched) if matched else []
        self._workload_available[provider.name] = any_success

    def _fetch_plugin_pods(self, provider: Provider, source: ProviderSource) -> None:
        """Sequential fallback paths, silent per-path catch, UID dedup
        (`IntelGpuDataContext.tsx:155-174`). Collected pods supplement
        the reactive pod list for clusters where the all-namespace list
        is RBAC-restricted but namespaced reads are allowed."""
        collected: list[Any] = []
        any_success = False
        for path in source.plugin_pod_paths:
            if collected and "labelSelector=" not in path:
                # Deliberate deviation from the reference, which always
                # merges all three paths (`IntelGpuDataContext.tsx:
                # 155-174`): namespace-wide fallbacks exist only for
                # installs whose labels no selector path matches. The
                # skip is gated on `collected`, which only holds pods
                # that passed `plugin_pod_filter` — so a selector path
                # must have found *confirmed* daemon pods before the
                # unfiltered whole-namespace list (thousands of pods at
                # fleet scale) is skipped. Daemon pods in the install
                # namespace matching neither selector are only missed in
                # the rare split-label install where other daemon pods
                # DID match a selector.
                continue
            try:
                data = self._transport.request(path, self._timeout_s)
            except ApiError:
                continue
            any_success = True
            collected.extend(
                p for p in obj.kube_list_items(data) if source.plugin_pod_filter(p)
            )
        # Total failure is recorded per-provider, NOT in the global error
        # banner — on a TPU-only cluster the Intel paths all failing is
        # expected, and polluting ClusterSnapshot.error with it would
        # break independent degradation (the same reasoning as the
        # reference's silent per-selector catch, `:162-164`).
        self._plugin_pod_errors[provider.name] = (
            None if any_success else "failed to query device-plugin pods"
        )
        self._fallback_plugin_pods[provider.name] = obj.dedup_by_uid(collected)

    # ------------------------------------------------------------------
    # Public API
    # ------------------------------------------------------------------

    def sync(self) -> ClusterSnapshot:
        """Run both tracks and return a fresh snapshot.

        A CLEAN tick — quiet watch stream, unchanged imperative results,
        stable error streams — preserves the previous snapshot object
        (with its lazily-computed fleet stats) and only advances
        ``fetched_at``: reclassifying an unchanged 1024-node fleet every
        background tick was the entire steady-state CPU cost."""
        old_errors = (self._node_error, self._pod_error)
        self._changed = False
        self._sync_reactive()
        self._sync_imperative()
        if (self._node_error, self._pod_error) != old_errors:
            self._changed = True
        if not self._changed and self._cached_snapshot is not None:
            self._cached_snapshot = replace(
                self._cached_snapshot, fetched_at=self._clock()
            )
            return self._cached_snapshot
        self._cached_snapshot = None
        return self.snapshot()

    def refresh(self) -> ClusterSnapshot:
        """Imperative track only — the ``refreshKey`` semantics
        (`:109-111`: hooks stay reactive, manual refresh re-fires the
        CRD/daemon-pod effect)."""
        self._refresh_count += 1
        self._sync_imperative(detect_changes=False)
        self._cached_snapshot = None
        return self.snapshot()

    def close(self) -> None:
        """Release the reactive-track worker thread. The single server
        context lives for the process, but bulk context creation (tests,
        embedding) would otherwise pin one idle thread per context until
        GC. Idempotent; a closed context can still sync (the pool is
        recreated lazily)."""
        pool = getattr(self, "_reactive_pool", None)
        if pool is not None:
            self._reactive_pool = None
            pool.shutdown(wait=False)

    def __enter__(self) -> "AcceleratorDataContext":
        return self

    def __exit__(self, *_exc: Any) -> None:
        self.close()

    def __del__(self) -> None:  # pragma: no cover — GC-timing dependent
        self.close()

    def snapshot(self) -> ClusterSnapshot:
        """The current snapshot. Built once per sync/refresh and cached —
        the ``useMemo`` discipline (`:200-208,228-251`): N page reads
        between syncs must not cost N fleet reclassifications."""
        if self._cached_snapshot is not None:
            return self._cached_snapshot
        self._cached_snapshot = self._build_snapshot()
        return self._cached_snapshot

    def _build_snapshot(self) -> ClusterSnapshot:
        views = classify_fleet(
            self._all_nodes or [], self._all_pods or [], self._providers
        )
        self._snapshot_generation += 1
        providers: dict[str, ProviderState] = {}
        for p in self._providers:
            view = views[p.name]
            view.version = self._snapshot_generation
            # Merge imperative-track plugin pods not already present in
            # the reactive list (UID dedup across tracks).
            seen = {obj.uid(pod) for pod in view.plugin_pods}
            for pod in self._fallback_plugin_pods.get(p.name, []):
                if obj.uid(pod) not in seen:
                    view.plugin_pods.append(pod)
            providers[p.name] = ProviderState(
                provider=p,
                view=view,
                workloads=list(self._workloads.get(p.name, [])),
                workload_available=self._workload_available.get(p.name, True),
                plugin_pods_error=self._plugin_pod_errors.get(p.name),
            )

        errors = [e for e in (self._node_error, self._pod_error) if e]
        return ClusterSnapshot(
            all_nodes=self._all_nodes,
            all_pods=self._all_pods,
            providers=providers,
            errors=errors,
            fetched_at=self._clock(),
            refresh_count=self._refresh_count,
        )
