"""State layer — the provider-agnostic AcceleratorDataContext.

Role-equivalent to the reference's single-source-of-truth context
(`/root/reference/src/api/IntelGpuDataContext.tsx`, ADR-001), lifted so
multiple accelerator providers (TPU, Intel GPU) share one snapshot and
degrade independently (the BASELINE north-star requirement).
"""

from .accelerator_context import (
    AcceleratorDataContext,
    ClusterSnapshot,
    ProviderState,
)
from .sources import (
    INTEL_SOURCE,
    ACTIVE_PODS_FIELD_SELECTOR,
    NODES_PATH,
    PODS_PATH,
    TPU_SOURCE,
    ProviderSource,
    default_sources,
)

__all__ = [
    "AcceleratorDataContext",
    "ClusterSnapshot",
    "ProviderState",
    "ProviderSource",
    "INTEL_SOURCE",
    "TPU_SOURCE",
    "ACTIVE_PODS_FIELD_SELECTOR",
    "NODES_PATH",
    "PODS_PATH",
    "default_sources",
]
