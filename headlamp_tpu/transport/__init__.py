"""Transport layer — how the framework talks to a Kubernetes API server.

Role-equivalent to the Headlamp SDK's ``ApiProxy.request`` used by the
reference (`/root/reference/src/api/IntelGpuDataContext.tsx:9,125`;
`/root/reference/src/api/metrics.ts:15,71`): a single JSON-over-HTTP
request function behind which all cluster access happens. Everything above
this layer is injectable/testable with :class:`MockTransport`.
"""

from .api_proxy import (
    ApiError,
    KubeTransport,
    MockTransport,
    RequestTimeout,
    Transport,
    WatchFeed,
    WatchTransport,
    with_timeout,
)
from .pool import (
    ConnectionPool,
    FanoutScheduler,
    PooledResponse,
    PoolExhausted,
    choose_width,
    fanout,
    pool_of,
)

__all__ = [
    "ApiError",
    "ConnectionPool",
    "FanoutScheduler",
    "KubeTransport",
    "MockTransport",
    "PooledResponse",
    "PoolExhausted",
    "RequestTimeout",
    "Transport",
    "WatchFeed",
    "WatchTransport",
    "choose_width",
    "fanout",
    "pool_of",
    "with_timeout",
]
