"""Keep-alive connection pool + RTT-aware fan-out scheduling (ADR-014).

BENCH_r05 put the scrape→paint p50 at 161 ms against a ~89 ms tunnel
RTT floor: the request path is round-trip-bound, not compute-bound, and
``urllib.request.urlopen`` paid a fresh TCP (+TLS) handshake for every
Kubernetes/Prometheus call — the discovery probe chain, the 16-query
instant fan-out, every list page. This module is the classic serving-
stack fix, with round-trip count treated as a first-class budget:

- :class:`ConnectionPool` — per-host keep-alive ``http.client``
  connections with a bounded concurrent-checkout cap, LRU idle
  eviction, and stale-socket detection with one transparent retry.
  Every open/reuse/eviction dual-accounts into per-pool ints (the
  /healthz view, bench deltas) and the process metric registry
  (/metricsz), and stamps ``transport.connect`` / ``transport.reuse``
  spans into the active request trace so saved round trips are visible
  in the ADR-013 waterfall.
- :class:`FanoutScheduler` — a persistent worker pool (no per-fetch
  ThreadPoolExecutor churn) whose fan-out *width* is chosen from the
  pool's measured RTT statistics: idle pooled sockets are free
  concurrency, while each socket beyond them costs a connect handshake
  that must pay for itself against the serial round-trip time it
  saves. Without a pool (MockTransport) it degrades to a fixed-width
  map over the same persistent workers.

Stdlib-only, like the rest of the transport layer: the pool must work
on a jax-less host and inside the test suite with zero extra deps.
"""

from __future__ import annotations

import http.client
import ssl
import threading
import time
import weakref
from typing import Any, Callable, Iterator, Sequence, TypeVar
from urllib.parse import urlsplit

from ..obs.metrics import registry as _metrics_registry
from ..obs.propagate import (
    TRACEPARENT_HEADER,
    current_traceparent,
    record_injected,
)
from ..obs.trace import span as _span

#: Concurrent checked-out connections per host. Matches the historical
#: fan-out ceiling (metrics/client.py capped its per-fetch executor at
#: 8): one warm fan-out can run full-width without ever queueing, and a
#: misbehaving caller cannot open an unbounded socket flood at the
#: apiserver.
DEFAULT_MAX_PER_HOST = 8

#: Idle keep-alive lifetime. kube-apiserver and the common proxies in
#: front of it close idle client connections well above this; evicting
#: first means the pool, not the peer, decides when a socket dies — a
#: peer-closed socket is exactly the stale-retry path this bound keeps
#: rare.
DEFAULT_IDLE_TTL_S = 60.0

#: EWMA smoothing for the per-pool connect/request RTT estimates the
#: fan-out width choice reads. 0.3 ≈ the last ~5 observations dominate:
#: reactive enough to follow a tunnel RTT shift, stable enough that one
#: outlier does not flip the width decision.
EWMA_ALPHA = 0.3

_T = TypeVar("_T")
_R = TypeVar("_R")

#: Failure modes of writing/reading on a kept-alive socket the peer
#: already closed — the retry-once set. Anything else (refused connect,
#: DNS, TLS handshake) fails loudly on fresh sockets too and must not
#: be retried into a double-send.
_STALE_ERRORS = (
    http.client.RemoteDisconnected,
    http.client.CannotSendRequest,
    http.client.ResponseNotReady,
    BrokenPipeError,
    ConnectionResetError,
    ConnectionAbortedError,
)

# Registry instruments (ADR-013 get-or-create: many pools per test
# process share one set). Per-pool ints stay the behavioral/bench view;
# these are the fleet-aggregable /metricsz view, written on the same
# code paths so the two surfaces can never disagree on a transition.
_OPENED = _metrics_registry.counter(
    "headlamp_tpu_transport_connections_opened_total",
    "TCP(+TLS) connections the transport pool opened, per host "
    "(each costs at least one extra round trip before the request).",
    labels=("host",),
)
_REUSED = _metrics_registry.counter(
    "headlamp_tpu_transport_connections_reused_total",
    "Requests served over an already-open pooled connection, per host "
    "(handshake round trips the pool saved).",
    labels=("host",),
)
_EVICTED = _metrics_registry.counter(
    "headlamp_tpu_transport_idle_evicted_total",
    "Idle pooled connections closed by TTL expiry or idle-slot overflow.",
)
_STALE_RETRIES = _metrics_registry.counter(
    "headlamp_tpu_transport_stale_retries_total",
    "Requests transparently retried on a fresh connection after a "
    "kept-alive socket turned out peer-closed.",
)
_CONNECT_HIST = _metrics_registry.histogram(
    "headlamp_tpu_transport_connect_latency_seconds",
    "TCP(+TLS) connection establishment latency, per host.",
    labels=("host",),
)
_CONNECT_FAILED = _metrics_registry.counter(
    "headlamp_tpu_transport_connect_failures_total",
    "TCP(+TLS) connection attempts that raised before a socket was "
    "established, per host.",
    labels=("host",),
)

#: Live pools, for the process-wide pool-size gauge: the registry's
#: callback gauge sums open connections across every pool still alive
#: (the server's one KubeTransport in production; many short-lived ones
#: under test).
_LIVE_POOLS: "weakref.WeakSet[ConnectionPool]" = weakref.WeakSet()

_metrics_registry.gauge_fn(
    "headlamp_tpu_transport_pool_connections_count",
    "Open pooled connections (idle + checked out) across live pools.",
    lambda: float(sum(p.open_connections for p in list(_LIVE_POOLS))),
)


class PoolExhausted(Exception):
    """Checkout blocked past its budget: every per-host slot stayed
    checked out. Callers see it via the transport's ApiError mapping —
    it signals local saturation, not a server failure."""


class _PooledConn:
    """One keep-alive connection plus the bookkeeping the pool needs:
    monotonic idle stamp (TTL eviction) and its host key."""

    __slots__ = ("raw", "key", "idle_since")

    def __init__(self, raw: http.client.HTTPConnection, key: tuple) -> None:
        self.raw = raw
        self.key = key
        self.idle_since = 0.0


class _HostSlot:
    """Per-(scheme, host, port) state: the idle stack, the checkout
    semaphore, and the open-connection count."""

    __slots__ = ("idle", "sem", "open_count", "lock")

    def __init__(self, max_per_host: int) -> None:
        #: MRU stack: reuse the most recently returned socket (warmest,
        #: least likely peer-closed) and let the stack's cold end age
        #: out through the TTL — LRU eviction, MRU reuse.
        self.idle: list[_PooledConn] = []
        self.sem = threading.BoundedSemaphore(max_per_host)
        self.open_count = 0
        self.lock = threading.Lock()


class PooledResponse:
    """A response whose connection returns to the pool on close.

    Reuse contract: the connection goes back only when the body was
    fully consumed (``isclosed``) and the server did not ask to close
    (``will_close``); anything else — abandoned mid-read, HTTP/1.0
    peer, ``Connection: close`` — discards the socket. ``close`` is
    idempotent and ALWAYS releases the checkout slot, which is the
    resource-leak guarantee the old ``urlopen`` sites lacked on their
    non-2xx raise paths."""

    def __init__(
        self,
        pool: "ConnectionPool",
        conn: _PooledConn,
        resp: http.client.HTTPResponse,
    ) -> None:
        self._pool = pool
        self._conn = conn
        self._resp = resp
        self._closed = False

    @property
    def status(self) -> int:
        return self._resp.status

    def read(self) -> bytes:
        return self._resp.read()

    def __iter__(self) -> Iterator[bytes]:
        return iter(self._resp)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        reusable = self._resp.isclosed() and not self._resp.will_close
        if not reusable:
            # Abandoned body or peer-terminated stream: the socket may
            # carry unread bytes and must never serve another request.
            self._resp.close()
        self._pool._release(self._conn, reusable=reusable)

    def __enter__(self) -> "PooledResponse":
        return self

    def __exit__(self, *_exc: Any) -> None:
        self.close()


class ConnectionPool:
    """Bounded per-host keep-alive pool over ``http.client``.

    Thread-safe: ThreadingHTTPServer request threads, the fan-out
    scheduler's workers, and ``with_timeout``'s per-call threads all
    check out concurrently. A checkout that would exceed
    ``max_per_host`` blocks up to the request timeout, then raises
    :class:`PoolExhausted` — backpressure, not a socket flood.

    ``monotonic`` is injectable for the idle-TTL tests (ADR-013 clock
    discipline: TTL math never touches wall clock)."""

    def __init__(
        self,
        *,
        max_per_host: int = DEFAULT_MAX_PER_HOST,
        max_idle_per_host: int | None = None,
        idle_ttl_s: float = DEFAULT_IDLE_TTL_S,
        monotonic: Callable[[], float] = time.monotonic,
    ) -> None:
        self.max_per_host = max_per_host
        self.max_idle_per_host = (
            max_idle_per_host if max_idle_per_host is not None else max_per_host
        )
        self.idle_ttl_s = idle_ttl_s
        self._mono = monotonic
        self._lock = threading.Lock()
        self._hosts: dict[tuple, _HostSlot] = {}
        # Per-pool plain ints (GIL-atomic increments under each slot's
        # lock): the /healthz ints and the bench's delta source. The
        # registry counters above are written on the same transitions.
        self.opened = 0
        self.reused = 0
        self.evicted = 0
        self.stale_retries = 0
        # RTT estimates feeding FanoutScheduler.choose_width. Aggregate
        # (not per-host): a pool fronts one apiserver base URL.
        self._connect_ewma_ms: float | None = None
        self._rtt_ewma_ms: float | None = None
        _LIVE_POOLS.add(self)

    # -- stats ---------------------------------------------------------

    @property
    def open_connections(self) -> int:
        with self._lock:
            slots = list(self._hosts.values())
        return sum(s.open_count for s in slots)

    def idle_count(self) -> int:
        with self._lock:
            slots = list(self._hosts.values())
        return sum(len(s.idle) for s in slots)

    def connect_ewma_ms(self) -> float | None:
        return self._connect_ewma_ms

    def rtt_ewma_ms(self) -> float | None:
        return self._rtt_ewma_ms

    def counters(self) -> dict[str, int]:
        """Monotone counters only, lock-free — the flight recorder's
        per-request delta view. Deliberately excludes the gauges and
        EWMAs snapshot() carries (open/idle connections, reuse_rate,
        RTT) whose movement would show up as noisy or negative
        'deltas' in a wide event."""
        return {
            "connections_opened": self.opened,
            "connections_reused": self.reused,
            "idle_evicted": self.evicted,
            "stale_retries": self.stale_retries,
        }

    def snapshot(self) -> dict[str, Any]:
        """The /healthz transport block: per-pool ints plus the live
        derived numbers an operator reads first (see OPERATIONS.md)."""
        total = self.opened + self.reused
        return {
            "connections_opened": self.opened,
            "connections_reused": self.reused,
            "idle_evicted": self.evicted,
            "stale_retries": self.stale_retries,
            "open_connections": self.open_connections,
            "idle_connections": self.idle_count(),
            "reuse_rate": round(self.reused / total, 4) if total else None,
            "connect_ewma_ms": (
                round(self._connect_ewma_ms, 2)
                if self._connect_ewma_ms is not None
                else None
            ),
            "rtt_ewma_ms": (
                round(self._rtt_ewma_ms, 2)
                if self._rtt_ewma_ms is not None
                else None
            ),
        }

    def _observe_connect(self, host_label: str, seconds: float) -> None:
        _CONNECT_HIST.observe(seconds, host=host_label)
        ms = seconds * 1000.0
        prev = self._connect_ewma_ms
        self._connect_ewma_ms = (
            ms if prev is None else prev + EWMA_ALPHA * (ms - prev)
        )

    def _observe_rtt(self, seconds: float) -> None:
        ms = seconds * 1000.0
        prev = self._rtt_ewma_ms
        self._rtt_ewma_ms = ms if prev is None else prev + EWMA_ALPHA * (ms - prev)

    # -- checkout / release --------------------------------------------

    def _slot(self, key: tuple) -> _HostSlot:
        with self._lock:
            slot = self._hosts.get(key)
            if slot is None:
                slot = self._hosts[key] = _HostSlot(self.max_per_host)
            return slot

    def _evict_expired(self, slot: _HostSlot, now: float) -> None:
        # Called under slot.lock. The idle list is MRU-ordered, so
        # expiry accumulates at the front; still scan the whole list —
        # it is ≤ max_idle_per_host entries.
        keep: list[_PooledConn] = []
        for conn in slot.idle:
            if now - conn.idle_since > self.idle_ttl_s:
                conn.raw.close()
                slot.open_count -= 1
                self.evicted += 1
                _EVICTED.inc()
            else:
                keep.append(conn)
        slot.idle[:] = keep

    def _checkout(
        self,
        key: tuple,
        timeout_s: float,
        context: ssl.SSLContext | None,
    ) -> tuple[_PooledConn, bool]:
        """One (connection, was_reused) under an acquired slot. The
        caller MUST route the connection into _release (normally via
        PooledResponse.close) or _discard+_release exactly once."""
        scheme, host, port = key
        slot = self._slot(key)
        if not slot.sem.acquire(timeout=max(timeout_s, 0.001)):
            raise PoolExhausted(
                f"{host}:{port}: all {self.max_per_host} pooled connections "
                f"stayed checked out for {timeout_s:g}s"
            )
        counted = False
        try:
            with slot.lock:
                self._evict_expired(slot, self._mono())
                if slot.idle:
                    conn = slot.idle.pop()
                    self.reused += 1
                    _REUSED.inc(host=f"{host}:{port}")
                    # Reused sockets carry the connect-time timeout of
                    # whichever request opened them; re-arm for this one.
                    if conn.raw.sock is not None:
                        conn.raw.sock.settimeout(timeout_s)
                    return conn, True
                slot.open_count += 1
                counted = True
            host_label = f"{host}:{port}"
            with _span("transport.connect", host=host_label):
                t0 = time.perf_counter()
                if scheme == "https":
                    raw: http.client.HTTPConnection = http.client.HTTPSConnection(
                        host, port, timeout=timeout_s, context=context
                    )
                else:
                    raw = http.client.HTTPConnection(host, port, timeout=timeout_s)
                try:
                    raw.connect()
                except Exception:
                    # Failed opens never reach the latency histogram, so
                    # they get their own counter — the transport_connect
                    # SLO's availability arm (ADR-016) feeds off it.
                    # Exception, not BaseException: a KeyboardInterrupt/
                    # SystemExit landing mid-connect is not a transport
                    # failure and must not spend the 0.1% error budget
                    # (the outer handler still undoes slot accounting).
                    _CONNECT_FAILED.inc(host=host_label)
                    raise
                self._observe_connect(host_label, time.perf_counter() - t0)
            self.opened += 1
            _OPENED.inc(host=host_label)
            return _PooledConn(raw, key), False
        except BaseException:
            # Failed open: the slot the semaphore reserved never
            # materialized into a connection — undo its accounting.
            if counted:
                self._drop_open_count(slot)
            slot.sem.release()
            raise

    def _drop_open_count(self, slot: _HostSlot) -> None:
        with slot.lock:
            if slot.open_count > 0:
                slot.open_count -= 1

    def _release(self, conn: _PooledConn, *, reusable: bool) -> None:
        slot = self._slot(conn.key)
        if reusable:
            with slot.lock:
                conn.idle_since = self._mono()
                slot.idle.append(conn)
                # Idle-slot overflow: evict the LRU end of the stack.
                while len(slot.idle) > self.max_idle_per_host:
                    victim = slot.idle.pop(0)
                    victim.raw.close()
                    slot.open_count -= 1
                    self.evicted += 1
                    _EVICTED.inc()
        else:
            conn.raw.close()
            self._drop_open_count(slot)
        slot.sem.release()

    def _discard(self, conn: _PooledConn) -> None:
        """Close a checked-out connection WITHOUT releasing its slot —
        the stale-retry path keeps the slot for its replacement so the
        retry cannot deadlock against a full pool."""
        conn.raw.close()
        self._drop_open_count(self._slot(conn.key))

    # -- the request entry point ---------------------------------------

    def request(
        self,
        url: str,
        *,
        headers: dict[str, str] | None = None,
        timeout_s: float = 2.0,
        context: ssl.SSLContext | None = None,
        method: str = "GET",
    ) -> PooledResponse:
        """Issue ``method url`` over a pooled connection and return the
        live response. The caller must close it (context manager) —
        close returns the connection to the pool when the body was
        fully read, and releases the checkout slot unconditionally.

        Stale-retry contract: a request that fails with a peer-closed
        symptom on a REUSED socket is retried exactly once on a fresh
        connection. Fresh-connection failures and second failures
        propagate — they are real errors, not keep-alive races."""
        parts = urlsplit(url)
        scheme = parts.scheme or "http"
        host = parts.hostname or ""
        port = parts.port or (443 if scheme == "https" else 80)
        key = (scheme, host, port)
        path = parts.path or "/"
        if parts.query:
            path += "?" + parts.query

        # ADR-028: the ONE place headlamp_tpu writes a ``traceparent``
        # request header (TRC001). Injected before the attempt loop so
        # a stale-retry reuses the same value — a retry is the same
        # logical request, not a new trace.
        send_headers = dict(headers) if headers else {}
        if TRACEPARENT_HEADER not in send_headers:
            traceparent = current_traceparent()
            if traceparent is not None:
                send_headers[TRACEPARENT_HEADER] = traceparent
                record_injected()

        slot = self._slot(key)
        for attempt in (0, 1):
            conn, reused = self._checkout(key, timeout_s, context)
            if reused:
                with _span("transport.reuse", host=f"{host}:{port}"):
                    pass
            t0 = time.perf_counter()
            try:
                conn.raw.request(method, path, headers=send_headers)
                resp = conn.raw.getresponse()
            except _STALE_ERRORS:
                self._discard(conn)
                if reused and attempt == 0:
                    self.stale_retries += 1
                    _STALE_RETRIES.inc()
                    # Keep the slot: _discard left the semaphore held,
                    # and the retry's _checkout would deadlock on a
                    # saturated pool waiting for our own slot.
                    slot.sem.release()
                    continue
                slot.sem.release()
                raise
            except BaseException:
                self._discard(conn)
                slot.sem.release()
                raise
            self._observe_rtt(time.perf_counter() - t0)
            return PooledResponse(self, conn, resp)
        raise AssertionError("unreachable: retry loop exits via return/raise")

    def close(self) -> None:
        """Close every idle connection (checked-out ones close through
        their PooledResponse). Idempotent; the pool stays usable."""
        with self._lock:
            slots = list(self._hosts.values())
        for slot in slots:
            with slot.lock:
                for conn in slot.idle:
                    conn.raw.close()
                    slot.open_count -= 1
                slot.idle.clear()


# ---------------------------------------------------------------------------
# RTT-aware fan-out scheduling
# ---------------------------------------------------------------------------

#: Upper bound on any single fan-out's width — the historical 8-worker
#: ceiling, now also the per-host checkout cap's partner: a full-width
#: fan-out exactly fills one host's pool and never queues behind itself.
DEFAULT_MAX_WIDTH = DEFAULT_MAX_PER_HOST

#: Workers in the shared executor. Two concurrent full-width fan-outs
#: (metrics route overlap + a background sync's provider chains) run
#: without queueing; beyond that requests queue instead of spawning
#: unbounded threads.
_EXECUTOR_WORKERS = 16


def choose_width(
    n_items: int,
    *,
    idle: int,
    connect_ms: float | None,
    rtt_ms: float | None,
    max_width: int = DEFAULT_MAX_WIDTH,
) -> int:
    """Fan-out width from pool state: how many sockets should ``n``
    queries spread across?

    Idle pooled sockets are free concurrency — reusing them costs no
    handshake, so width starts there (at least 1). Each socket BEYOND
    the idle set costs one connect (measured: ``connect_ms``), which is
    only worth paying while it saves more serial round-trip time than
    it costs: going from width w to w+1 saves ``rtt_ms * n * (1/w -
    1/(w+1))`` of serial queue time. With no measurements yet (cold
    pool, mock transport) there is nothing to budget against and the
    historical full width applies."""
    cap = max(1, min(n_items, max_width))
    if n_items <= 1:
        return cap
    if connect_ms is None or rtt_ms is None:
        return cap
    width = max(1, min(idle, cap))
    while width < cap:
        serial_saving_ms = rtt_ms * n_items * (1.0 / width - 1.0 / (width + 1))
        if serial_saving_ms <= connect_ms:
            break
        width += 1
    return width


class FanoutScheduler:
    """Persistent fan-out workers + the width policy above.

    One process-wide instance (``fanout``) replaces the per-call
    ``ThreadPoolExecutor`` churn in the Prometheus clients and the
    context's imperative track: thread creation is not free (~100 µs a
    thread, paid 16× per metrics fetch before this), and a persistent
    pool also gives the width policy a stable place to live.

    Work is partitioned into ``width`` chunks, each chunk running its
    items serially on one worker — so at most ``width`` transport
    connections are in flight for this fan-out, which is exactly the
    invariant the width policy prices. Workers inherit the caller's
    contextvars (``contextvars.copy_context``) so transport/metrics
    spans land in the live request trace."""

    def __init__(self, *, max_width: int = DEFAULT_MAX_WIDTH) -> None:
        self.max_width = max_width
        self._lock = threading.Lock()
        self._executor: Any = None

    def _pool_executor(self) -> Any:
        if self._executor is None:
            with self._lock:
                if self._executor is None:
                    import concurrent.futures

                    self._executor = concurrent.futures.ThreadPoolExecutor(
                        max_workers=_EXECUTOR_WORKERS,
                        thread_name_prefix="hl-tpu-fanout",
                    )
        return self._executor

    def width_for(self, n_items: int, pool: ConnectionPool | None) -> int:
        if pool is None:
            return max(1, min(n_items, self.max_width))
        return choose_width(
            n_items,
            idle=pool.idle_count(),
            connect_ms=pool.connect_ewma_ms(),
            rtt_ms=pool.rtt_ewma_ms(),
            max_width=min(self.max_width, pool.max_per_host),
        )

    def map(
        self,
        fn: Callable[[_T], _R],
        items: Sequence[_T],
        *,
        pool: ConnectionPool | None = None,
    ) -> list[_R]:
        """``[fn(x) for x in items]`` at the chosen width, results in
        input order. Exceptions propagate (the Prometheus clients wrap
        ``fn`` in their own per-query ApiError catch, so a raise here
        is a programming error, not a network blip)."""
        n = len(items)
        if n == 0:
            return []
        width = self.width_for(n, pool)
        if width <= 1 or n == 1:
            return [fn(item) for item in items]
        import contextvars

        executor = self._pool_executor()
        chunks = [list(range(i, n, width)) for i in range(width)]

        def run_chunk(indices: list[int]) -> list[tuple[int, _R]]:
            return [(i, fn(items[i])) for i in indices]

        futures = [
            executor.submit(contextvars.copy_context().run, run_chunk, chunk)
            for chunk in chunks
        ]
        out: list[Any] = [None] * n
        for future in futures:
            for i, result in future.result():
                out[i] = result
        return out


#: THE process fan-out scheduler — the Prometheus clients and the
#: context's imperative track share its workers.
fanout = FanoutScheduler()


def pool_of(transport: Any) -> ConnectionPool | None:
    """The transport's connection pool when it has one (KubeTransport),
    else None (MockTransport and friends) — the seam fan-out callers
    use so width policy engages exactly when real sockets are in play."""
    pool = getattr(transport, "pool", None)
    return pool if isinstance(pool, ConnectionPool) else None
