"""Kubernetes API transport: the ``ApiProxy.request`` contract.

The reference funnels every cluster call through one function —
``ApiProxy.request(path) -> parsed JSON`` — and wraps each imperative call
in a 2 s timeout (`/root/reference/src/api/IntelGpuDataContext.tsx:72-82`).
This module provides the same contract for Python:

- :class:`Transport` — the protocol (``request(path, timeout_s)``).
- :func:`with_timeout` — hard wall-clock cap on any callable, the analogue
  of the reference's ``withTimeout`` Promise.race.
- :class:`KubeTransport` — real HTTP against an API server base URL
  (``kubectl proxy``, or in-cluster with a service-account bearer
  token), over the keep-alive :class:`~headlamp_tpu.transport.pool.
  ConnectionPool` (ADR-014) so repeat calls reuse sockets instead of
  paying a fresh TCP+TLS handshake per round trip.
- :class:`MockTransport` — the test double: path -> canned response /
  exception, with call recording (mirrors the vitest
  ``ApiProxy.request`` mocks, `IntelGpuDataContext.test.tsx:7-15`).
"""

from __future__ import annotations

import contextvars
import json
import ssl
import threading
from typing import Any, Callable, Mapping, Protocol

from .pool import ConnectionPool, PoolExhausted

#: Default per-request timeout, matching the reference's 2 000 ms
#: (`IntelGpuDataContext.tsx:72`).
DEFAULT_TIMEOUT_S = 2.0


class ApiError(Exception):
    """A request failed (HTTP error, bad JSON, connection refused)."""

    def __init__(self, path: str, message: str, status: int | None = None) -> None:
        super().__init__(f"{path}: {message}")
        self.path = path
        self.status = status


class RequestTimeout(ApiError):
    """The request exceeded its wall-clock budget."""

    def __init__(self, path: str, timeout_s: float) -> None:
        super().__init__(path, f"timed out after {timeout_s:g}s")
        self.timeout_s = timeout_s


class Transport(Protocol):
    """Single entry point for cluster JSON requests."""

    def request(self, path: str, timeout_s: float = DEFAULT_TIMEOUT_S) -> Any:
        """GET ``path`` and return parsed JSON. Raises :class:`ApiError`
        (or :class:`RequestTimeout`) on failure; never returns partial
        data."""
        ...


class WatchTransport(Protocol):
    """Optional transport extension: bounded Kubernetes watch.

    A watch request (``?watch=true&resourceVersion=N&timeoutSeconds=S``)
    is a normal GET whose body is newline-delimited JSON events the
    apiserver streams until ``timeoutSeconds`` elapses — so a
    request/response transport can serve it as a *batch delta poll*:
    collect the whole bounded stream, return the parsed events. The
    context degrades to full re-lists when a transport lacks this method
    (checked with ``hasattr``, mirroring how the reference only gets
    live updates where the SDK provides ``useList``'s watch)."""

    def watch(self, path: str, timeout_s: float = DEFAULT_TIMEOUT_S) -> list[Any]:
        """GET a bounded watch stream; return its parsed event objects
        (``{"type": "ADDED"|"MODIFIED"|"DELETED"|"BOOKMARK"|"ERROR",
        "object": {...}}``) in arrival order. Raises :class:`ApiError`
        on transport failure (HTTP 410 ⇒ the caller must re-list)."""
        ...


def with_timeout(fn: Callable[[], Any], timeout_s: float, path: str = "") -> Any:
    """Run ``fn`` with a hard wall-clock cap — the reference's
    ``withTimeout`` (`IntelGpuDataContext.tsx:72-82`). On expiry raises
    :class:`RequestTimeout`; the abandoned call keeps running in its
    daemon thread but its result is discarded. One fresh thread per call
    (not a shared pool): urllib's socket timeout does not cover DNS
    resolution, so a stalled resolver can park threads indefinitely — a
    bounded pool would exhaust and then spuriously time out every later
    request against a healthy server. The worker runs under the
    caller's copied contextvars, so the pool's ``transport.connect`` /
    ``transport.reuse`` spans land in the live request trace (plain
    threads inherit nothing; same discipline as the fan-out workers)."""
    outcome: dict[str, Any] = {}
    ctx = contextvars.copy_context()

    def runner() -> None:
        try:
            outcome["value"] = ctx.run(fn)
        except BaseException as e:  # noqa: BLE001 — re-raised in caller
            outcome["error"] = e

    thread = threading.Thread(target=runner, daemon=True, name="hl-tpu-timeout")
    thread.start()
    thread.join(timeout_s)
    if thread.is_alive():
        raise RequestTimeout(path, timeout_s)
    if "error" in outcome:
        raise outcome["error"]
    return outcome.get("value")


class KubeTransport:
    """Real API-server transport over pooled keep-alive HTTP.

    ``base_url`` examples:
    - ``http://127.0.0.1:8001`` (kubectl proxy — no auth needed)
    - ``https://10.0.0.1`` in-cluster, with ``bearer_token`` from the
      mounted service account and ``ca_cert`` for verification.

    Every request runs over :attr:`pool` (one pool per transport —
    injectable for tests), so a warm scrape→paint request reuses the
    sockets the previous one opened instead of re-handshaking per call
    (ADR-014). The pool also guarantees the response object is closed
    on every exit path, including non-2xx raises — the resource leak
    the previous ``urlopen`` sites had (``urllib.error.HTTPError`` IS
    the open response; raising it out of the ``with`` left its fp to
    the GC).
    """

    def __init__(
        self,
        base_url: str,
        *,
        bearer_token: str | None = None,
        ca_cert: str | None = None,
        insecure_skip_verify: bool = False,
        pool: ConnectionPool | None = None,
    ) -> None:
        self.base_url = base_url.rstrip("/")
        self.pool = pool if pool is not None else ConnectionPool()
        self._headers: dict[str, str] = {"Accept": "application/json"}
        if bearer_token:
            self._headers["Authorization"] = f"Bearer {bearer_token}"
        if ca_cert:
            self._ssl_context: ssl.SSLContext | None = ssl.create_default_context(
                cafile=ca_cert
            )
        elif insecure_skip_verify:
            ctx = ssl.create_default_context()
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
            self._ssl_context = ctx
        else:
            self._ssl_context = None

    @classmethod
    def in_cluster(cls) -> "KubeTransport":
        """Build from the standard in-cluster service-account mount."""
        sa = "/var/run/secrets/kubernetes.io/serviceaccount"
        with open(f"{sa}/token", encoding="utf-8") as f:
            token = f.read().strip()
        return cls(
            "https://kubernetes.default.svc",
            bearer_token=token,
            ca_cert=f"{sa}/ca.crt",
        )

    def request(self, path: str, timeout_s: float = DEFAULT_TIMEOUT_S) -> Any:
        url = self.base_url + (path if path.startswith("/") else "/" + path)

        def do_request() -> Any:
            import http.client

            try:
                with self.pool.request(
                    url,
                    headers=self._headers,
                    timeout_s=timeout_s,
                    context=self._ssl_context,
                ) as resp:
                    # Read the body BEFORE the status check: a fully
                    # drained response is what lets close() return the
                    # connection to the pool, and error bodies (k8s
                    # Status objects) are tiny.
                    body = resp.read()
                    if not 200 <= resp.status < 300:
                        raise ApiError(
                            path, f"HTTP {resp.status}", status=resp.status
                        )
            except PoolExhausted as e:
                raise ApiError(path, f"connection pool exhausted: {e}") from e
            except (OSError, http.client.HTTPException) as e:
                # Refused connect, reset mid-read, truncated chunk, TLS
                # failure — callers must see ApiError, never a raw
                # socket exception.
                raise ApiError(path, f"request failed: {e}") from e
            try:
                return json.loads(body)
            except json.JSONDecodeError as e:
                raise ApiError(path, f"invalid JSON: {e}") from e

        return with_timeout(do_request, timeout_s, path)

    def watch(self, path: str, timeout_s: float = DEFAULT_TIMEOUT_S) -> list[Any]:
        """Bounded watch: read the NDJSON event stream until the server
        closes it (it will, after the ``timeoutSeconds`` the caller put
        in ``path``). ``timeout_s`` is the *client* budget and must
        exceed the server-side window — the caller owns that margin."""
        url = self.base_url + (path if path.startswith("/") else "/" + path)

        def do_request() -> list[Any]:
            import http.client

            events: list[Any] = []
            try:
                with self.pool.request(
                    url,
                    headers=self._headers,
                    timeout_s=timeout_s,
                    context=self._ssl_context,
                ) as resp:
                    if not 200 <= resp.status < 300:
                        resp.read()
                        raise ApiError(
                            path, f"HTTP {resp.status}", status=resp.status
                        )
                    for raw in resp:
                        line = raw.strip()
                        if not line:
                            continue
                        events.append(json.loads(line))
            except PoolExhausted as e:
                raise ApiError(path, f"connection pool exhausted: {e}") from e
            except (OSError, http.client.HTTPException) as e:
                # Long-lived watch streams get cut mid-body far more
                # often than short GETs complete abnormally: a reset or
                # truncated chunk raises ConnectionResetError /
                # IncompleteRead here, and it must surface as ApiError
                # so the context's fall-back-to-relist path engages.
                raise ApiError(path, f"watch stream failed: {e}") from e
            except json.JSONDecodeError as e:
                raise ApiError(path, f"invalid watch JSON: {e}") from e
            return events

        return with_timeout(do_request, timeout_s, path)


class WatchFeed:
    """Mock apiserver state for one watchable list: current objects plus
    a bounded event log keyed by resourceVersion. Tests and the demo
    server mutate it with :meth:`push`; the paginated LIST response and
    the watch-delta response both derive from it, so a context driven
    against it sees exactly the list+watch protocol contract (including
    410 Gone after :meth:`compact`)."""

    def __init__(self, items: list[Any], resource_version: int = 1000) -> None:
        self._items: dict[str, Any] = {}
        for item in items:
            self._items[self._uid(item)] = item
        self.resource_version = int(resource_version)
        #: (resource_version, event) pairs, oldest first.
        self.events: list[tuple[int, dict]] = []
        #: Oldest resourceVersion still replayable; watches asking for
        #: anything older get the apiserver's 410 Gone ERROR event.
        self.oldest_retained = int(resource_version)

    @staticmethod
    def _uid(item: Any) -> str:
        metadata = item.get("metadata", {}) if isinstance(item, Mapping) else {}
        return str(metadata.get("uid") or metadata.get("name") or id(item))

    def push(self, event_type: str, obj: Any) -> None:
        """Record an ADDED/MODIFIED/DELETED/BOOKMARK event; object
        events also apply to the current state (BOOKMARK only advances
        the resourceVersion, exactly like the apiserver's)."""
        self.resource_version += 1
        if event_type == "DELETED":
            self._items.pop(self._uid(obj), None)
        elif event_type != "BOOKMARK":
            self._items[self._uid(obj)] = obj
        self.events.append((self.resource_version, {"type": event_type, "object": obj}))

    def compact(self) -> None:
        """Forget the event log — subsequent watches from any older
        resourceVersion get 410 Gone, forcing the client's re-list path
        (the apiserver does this when its watch cache window expires)."""
        self.oldest_retained = self.resource_version
        self.events.clear()

    def list_response(self, req_path: str) -> Any:
        """Kubernetes LIST honoring ``limit``/``continue`` pagination,
        stamped with the feed's current resourceVersion."""
        import urllib.parse

        items = list(self._items.values())
        query = urllib.parse.parse_qs(urllib.parse.urlparse(req_path).query)
        limit = int(query.get("limit", ["0"])[0] or 0)
        metadata: dict[str, Any] = {"resourceVersion": str(self.resource_version)}
        if not limit:
            return {"kind": "List", "metadata": metadata, "items": items}
        offset = int(query.get("continue", ["0"])[0] or 0)
        page = items[offset : offset + limit]
        next_offset = offset + limit
        if next_offset < len(items):
            metadata["continue"] = str(next_offset)
        return {"kind": "List", "metadata": metadata, "items": page}

    def events_since(self, resource_version: str) -> list[Any]:
        """The watch response for ``resourceVersion=N``: every event
        newer than N, or a single 410 ERROR event when N predates the
        retained window."""
        try:
            rv = int(resource_version)
        except (TypeError, ValueError):
            rv = 0
        if rv < self.oldest_retained:
            return [
                {
                    "type": "ERROR",
                    "object": {
                        "kind": "Status",
                        "code": 410,
                        "reason": "Expired",
                        "message": f"too old resource version: {rv}",
                    },
                }
            ]
        out: list[Any] = []
        for ev_rv, event in self.events:
            if ev_rv <= rv:
                continue
            # Stamp each event object's resourceVersion the way the
            # apiserver does — clients advance their cursor from it.
            obj = dict(event["object"]) if isinstance(event["object"], Mapping) else {}
            metadata = dict(obj.get("metadata", {}))
            metadata["resourceVersion"] = str(ev_rv)
            obj["metadata"] = metadata
            out.append({"type": event["type"], "object": obj})
        return out


class MockTransport:
    """Canned-response transport for tests and the demo server.

    ``routes`` maps a path (exact string or a predicate-friendly prefix
    via :meth:`add_prefix`) to either a JSON-shaped value, an Exception
    instance (raised), or a zero-arg callable (invoked per request — use
    for sequenced responses). Unrouted paths raise ``ApiError`` with
    status 404, matching an apiserver's behaviour for absent CRDs.
    """

    #: Query parameters a paginated list request may carry and still be
    #: served by an :meth:`add_list` route (anything else — e.g. a
    #: labelSelector — must be routed explicitly).
    _LIST_PARAMS = frozenset({"limit", "continue", "fieldSelector", "resourceVersion"})

    def __init__(self, routes: Mapping[str, Any] | None = None) -> None:
        self.routes: dict[str, Any] = dict(routes or {})
        self._prefix_routes: list[tuple[str, Any]] = []
        self._list_routes: dict[str, Any] = {}
        self._overrides: list[tuple[str, Any]] = []
        self._watch_feeds: dict[str, WatchFeed] = {}
        self.calls: list[str] = []
        self.watch_calls: list[str] = []

    def add(self, path: str, response: Any) -> None:
        self.routes[path] = response

    def add_prefix(self, prefix: str, response: Any) -> None:
        self._prefix_routes.append((prefix, response))

    def add_override(self, prefix: str, response: Any) -> None:
        """Route checked before everything else (last registered wins) —
        the test hook for 'break this endpoint regardless of pagination'.
        A query-less prefix matches the endpoint itself and its
        limit/continue/fieldSelector forms, but NOT selector sub-queries
        (``?labelSelector=``) — those are distinct fallback paths with
        their own routes; break them with an explicit ``?labelSelector``
        prefix."""
        self._overrides.append((prefix, response))

    def _override_matches(self, path: str, prefix: str) -> bool:
        import urllib.parse

        if "?" in prefix:
            return path.startswith(prefix)
        parsed = urllib.parse.urlparse(path)
        if not parsed.path.startswith(prefix):
            return False
        params = set(urllib.parse.parse_qs(parsed.query))
        return not (params - self._LIST_PARAMS)

    def add_list(self, path: str, items: list[Any]) -> None:
        """Serve a Kubernetes list at ``path`` honoring ``limit=`` /
        ``continue=`` pagination the way the apiserver does (continue
        tokens are opaque to clients; here they are plain offsets).
        Requests with no ``limit`` get the whole list. fieldSelector /
        resourceVersion params are accepted and ignored (the mock does
        not filter); a labelSelector query does NOT match — selector
        routes stay explicit."""
        import urllib.parse

        def respond(req_path: str) -> Any:
            query = urllib.parse.parse_qs(urllib.parse.urlparse(req_path).query)
            limit = int(query.get("limit", ["0"])[0] or 0)
            if not limit:
                return {"kind": "List", "items": list(items)}
            offset = int(query.get("continue", ["0"])[0] or 0)
            page = items[offset : offset + limit]
            next_offset = offset + limit
            metadata = (
                {"continue": str(next_offset)} if next_offset < len(items) else {}
            )
            return {"kind": "List", "metadata": metadata, "items": page}

        self._list_routes[path] = respond

    def add_watchable_list(
        self, path: str, items: list[Any], resource_version: int = 1000
    ) -> WatchFeed:
        """Serve ``path`` as a live list+watch source: LIST requests get
        paginated responses stamped with the feed's resourceVersion,
        watch requests get the deltas pushed since the requested cursor.
        Returns the :class:`WatchFeed` — mutate it with ``push``/
        ``compact`` to drive the scenario."""
        feed = WatchFeed(items, resource_version)
        self._list_routes[path] = feed.list_response
        self._watch_feeds[path] = feed
        return feed

    def watch(self, path: str, timeout_s: float = DEFAULT_TIMEOUT_S) -> list[Any]:
        """Watch requests route like any other (overrides and exact
        routes can inject failures), then fall through to the registered
        :class:`WatchFeed` for the endpoint. No feed ⇒ 404, matching an
        apiserver that has the resource but this mock wasn't told to
        watch — callers must treat it as 'watch unsupported, re-list'."""
        import urllib.parse

        self.watch_calls.append(path)
        for prefix, response in reversed(self._overrides):
            if path.startswith(prefix):
                return self._resolve(path, response)
        if path in self.routes:
            return self._resolve(path, self.routes[path])
        parsed = urllib.parse.urlparse(path)
        feed = self._watch_feeds.get(parsed.path)
        if feed is not None:
            query = urllib.parse.parse_qs(parsed.query)
            rv = query.get("resourceVersion", ["0"])[0]
            return feed.events_since(rv)
        raise ApiError(path, "HTTP 404", status=404)

    def _match_list_route(self, path: str) -> Any | None:
        import urllib.parse

        parsed = urllib.parse.urlparse(path)
        respond = self._list_routes.get(parsed.path)
        if respond is None:
            return None
        params = set(urllib.parse.parse_qs(parsed.query))
        if params - self._LIST_PARAMS:
            return None
        return respond

    def request(self, path: str, timeout_s: float = DEFAULT_TIMEOUT_S) -> Any:
        self.calls.append(path)
        for prefix, response in reversed(self._overrides):
            if self._override_matches(path, prefix):
                return self._resolve(path, response)
        if path in self.routes:
            return self._resolve(path, self.routes[path])
        list_route = self._match_list_route(path)
        if list_route is not None:
            return self._resolve(path, list_route)
        for prefix, response in self._prefix_routes:
            if path.startswith(prefix):
                return self._resolve(path, response)
        raise ApiError(path, "HTTP 404", status=404)

    def _resolve(self, path: str, response: Any) -> Any:
        if isinstance(response, Exception):
            raise response
        if callable(response):
            # Callables may take the request path (dynamic routes like
            # query_range, whose response must echo requested
            # timestamps) or nothing (simple sequenced responses). The
            # call form is chosen by signature, not try/except — a
            # TypeError raised *inside* the callable must surface as
            # the real bug, not as a dispatch retry.
            import inspect

            try:
                takes_path = len(inspect.signature(response).parameters) >= 1
            except (TypeError, ValueError):  # builtins without signatures
                takes_path = False
            produced = response(path) if takes_path else response()
            return self._resolve(path, produced)
        return response
